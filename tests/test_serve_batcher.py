"""Micro-batcher units (consensus_specs_tpu/serve/batcher.py):
admission control on the bounded queue, cross-client accumulation +
dedup, the pure-function result cache, host-oracle degradation of a
chaos-faulted flush, and drain semantics (every accepted check answered
exactly once)."""
import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import resilience
from consensus_specs_tpu.serve.batcher import (
    Draining,
    QueueFull,
    VerifyBatcher,
)


@pytest.fixture(scope="module")
def valid_check():
    """One real valid FastAggregateVerify check (module-scoped: the
    pure-python pairing is ~0.5s)."""
    from consensus_specs_tpu.crypto.bls import ciphersuite as oracle
    from consensus_specs_tpu.crypto.bls.fields import R

    sks = [21, 22]
    pks = tuple(oracle.SkToPk(sk) for sk in sks)
    msg = b"\x77" * 32
    sig = oracle.Sign(sum(sks) % R, msg)
    return ("fav", pks, msg, sig)


def garbage_check(i: int):
    """Well-formed but invalid key: resolves False fast (the reference
    oracle rejects the pubkey) — no pairing cost in queue-shape tests."""
    return ("fav", (bytes([i % 251 + 1]) * 48,), b"\x01" * 32, b"\x02" * 96)


def test_queue_full_rejects_at_admission():
    b = VerifyBatcher(max_queue=4, cache_size=0)  # flusher NOT started
    b._enqueue([garbage_check(i) for i in range(4)])
    with pytest.raises(QueueFull):
        b._enqueue([garbage_check(99)])
    assert b.rejected == 1 and b.accepted == 4
    # all-or-nothing: a 2-key batch against 1 free slot rejects BOTH
    b2 = VerifyBatcher(max_queue=5, cache_size=0)
    b2._enqueue([garbage_check(i) for i in range(4)])
    with pytest.raises(QueueFull):
        b2._enqueue([garbage_check(8), garbage_check(9)])
    assert b2.depth() == 4


def test_flush_resolves_and_caches(valid_check):
    b = VerifyBatcher(linger_ms=1).start()
    try:
        assert b.submit(valid_check, timeout_s=60) is True
        assert b.cache_stats()["size"] >= 1
        hits_before = b.cache_hits
        assert b.submit(valid_check, timeout_s=60) is True  # cache hit
        assert b.cache_hits == hits_before + 1
        assert b.flushed_rows == 1  # the hit never re-dispatched
    finally:
        b.drain(10)


def test_concurrent_submits_share_one_flush(valid_check):
    """N threads submitting the same key while the flusher lingers must
    collapse to ONE dispatched row (the facade dedups by key)."""
    b = VerifyBatcher(linger_ms=150, cache_size=0).start()
    results = []
    try:
        def worker():
            results.append(b.submit(valid_check, timeout_s=60))

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert results == [True] * 6
        assert b.flushes == 1, "one linger window -> one flush"
        assert b.flushed_rows == 6  # six accepted entries, one dispatch row
    finally:
        b.drain(10)


def test_chaos_faulted_flush_degrades_to_oracle(valid_check):
    """A fault injected at the serve.flush site mid-batch: the whole
    batch degrades to the per-row host oracle and every client still
    gets the bit-exact answer (valid -> True, garbage -> False)."""
    b = VerifyBatcher(linger_ms=150, cache_size=0).start()
    try:
        with resilience.inject("serve.flush", "deterministic", count=1):
            results = {}

            def worker(name, key):
                results[name] = b.submit(key, timeout_s=60)

            threads = [
                threading.Thread(target=worker, args=("valid", valid_check)),
                threading.Thread(target=worker, args=("bad", garbage_check(3))),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
        assert results == {"valid": True, "bad": False}
        events = [e for e in resilience.events()
                  if e["event"] == "fallback" and e["domain"] == "serve.flush"]
        assert events, "oracle degradation must be a recorded event"
    finally:
        b.drain(10)


def test_drain_answers_everything_once():
    """Checks queued behind a long linger window at drain time: drain()
    flushes them all — answered exactly once, none dropped."""
    b = VerifyBatcher(linger_ms=60_000, cache_size=0).start()
    keys = [garbage_check(i) for i in range(12)]
    answers = {}

    def worker(i):
        answers[i] = b.submit(keys[i], timeout_s=60)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    while b.depth() < 12:
        pass
    assert b.drain(30) is True
    for t in threads:
        t.join(30)
    assert sorted(answers) == list(range(12))
    assert set(answers.values()) == {False}
    assert b.accepted == 12 and b.flushed_rows == 12
    with pytest.raises(Draining):
        b.submit(garbage_check(50))
