"""Unit coverage for the cross-case batch scheduler (consensus_specs_tpu/
sched): the flush planner's canonical bucket shapes and pad accounting,
the bounded supervised writer (ordering, backpressure, retry, terminal
failure surfacing), the bucketed DeferredVerifier flush against a fake
cold backend (including the chaos-degraded per-row fallback), and the
persistent compile cache's knob resolution + real cross-process reuse."""
from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import threading
import time

import pytest

from consensus_specs_tpu.sched import (
    CaseWriter,
    compile_cache,
    plan_flush,
    pow2_bucket,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# bucketing
# ---------------------------------------------------------------------------

def test_pow2_bucket():
    assert pow2_bucket(0) == 1
    assert pow2_bucket(1) == 1
    assert pow2_bucket(3) == 4
    assert pow2_bucket(8) == 8
    assert pow2_bucket(9) == 16
    assert pow2_bucket(1, minimum=8) == 8
    assert pow2_bucket(3, minimum=2) == 4
    # non-pow2 minimum rounds up to the next pow2
    assert pow2_bucket(1, minimum=6) == 8


def test_plan_flush_groups_by_width_bucket():
    # 1-key ops checks, 64-key attestation aggregates, 512-key sync rows:
    # three K shapes, never cross-padded
    widths = [1] * 10 + [64] * 5 + [512] * 2
    plan = plan_flush(widths, min_rows=8, max_rows=128, min_keys=2)
    ks = sorted(d.k_bucket for d in plan.dispatches)
    assert ks == [2, 64, 512]
    assert plan.total_rows == 17
    # all indices covered exactly once
    covered = sorted(i for d in plan.dispatches for i in d.indices)
    assert covered == list(range(17))
    # row padding to pow2 above the floor
    by_k = {d.k_bucket: d for d in plan.dispatches}
    assert by_k[2].row_bucket == 16 and by_k[2].pad_rows == 6
    assert by_k[64].row_bucket == 8 and by_k[64].pad_rows == 3
    # the O(#buckets) compile bound is visible in the plan
    assert len(plan.shapes) == 3


def test_plan_flush_chunks_under_row_cap():
    plan = plan_flush([1] * 300, min_rows=8, max_rows=128, min_keys=2)
    assert [d.rows for d in plan.dispatches] == [128, 128, 44]
    # one compiled K shape; two row shapes (128 and the 64-pad tail)
    assert {d.k_bucket for d in plan.dispatches} == {2}
    assert plan.dispatches[-1].row_bucket == 64


def test_plan_flush_pad_accounting():
    plan = plan_flush([1, 1], min_rows=8, max_rows=128, min_keys=2)
    (d,) = plan.dispatches
    # 8 rows x 2 keys = 16 slots; 2 real pairs -> 87.5% padding
    assert d.slot_waste_pct == 87.5
    assert d.stats()["pad_rows"] == 6


def test_plan_flush_empty_and_dedup_stat():
    assert plan_flush([]).dispatches == []
    assert plan_flush([1, 2], dedup_hits=7).stats()["dedup_hits"] == 7


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def test_writer_preserves_submit_order_under_backpressure():
    out = []

    def slow_commit(i):
        time.sleep(0.001)
        out.append(i)

    w = CaseWriter(slow_commit, maxsize=2)
    for i in range(50):
        w.submit(f"case{i}", i)
    assert w.close() == []
    assert out == list(range(50))
    assert w.written == 50
    assert w.backpressure_waits > 0  # the bound actually engaged


def test_writer_retries_injected_transients():
    from consensus_specs_tpu.resilience import inject

    out = []
    w = CaseWriter(out.append)
    with inject("sched.writer", "transient", count=2):
        w.submit("case0", "a")
        assert w.close() == []
    assert out == ["a"] and w.written == 1


def test_writer_surfaces_terminal_failures():
    calls = []

    def commit(i):
        calls.append(i)
        if i == 1:
            raise ValueError("disk on fire")

    w = CaseWriter(commit)
    for i in range(3):
        w.submit(f"case{i}", i)
    failures = w.close()
    assert [label for label, _ in failures] == ["case1"]
    assert "disk on fire" in failures[0][1]
    assert w.written == 2  # the other cases still landed
    # close() is idempotent and submit-after-close is refused
    assert w.close() == failures
    with pytest.raises(AssertionError):
        w.submit("late", 9)


def test_writer_runs_on_one_background_thread():
    tids = set()
    w = CaseWriter(lambda: tids.add(threading.get_ident()))
    for i in range(5):
        w.submit(f"c{i}")
    w.close()
    assert len(tids) == 1 and threading.get_ident() not in tids


# ---------------------------------------------------------------------------
# bucketed DeferredVerifier flush (fake cold backend)
# ---------------------------------------------------------------------------

class _FakeColdBackend:
    """Reference-answering backend exposing the cold batch pipeline +
    shape floors, recording the dispatched batch shapes."""

    def __init__(self):
        from consensus_specs_tpu.crypto.bls import ciphersuite

        self._ref = ciphersuite
        self.batches = []

    def __getattr__(self, name):
        return getattr(self._ref, name)

    def cold_shape_floors(self):
        return 4, 16, 2

    def fast_aggregate_verify_batch_cold(self, pubkey_lists, messages, signatures):
        self.batches.append([len(p) for p in pubkey_lists])
        return [self._ref.FastAggregateVerify(list(p), m, s)
                for p, m, s in zip(pubkey_lists, messages, signatures)]


@pytest.fixture
def fake_cold_backend(monkeypatch):
    from consensus_specs_tpu.crypto import bls

    fake = _FakeColdBackend()
    monkeypatch.setattr(bls, "_backend", fake)
    monkeypatch.setattr(bls, "_backend_name", "fake")
    yield fake


def test_flush_dispatches_per_width_bucket(fake_cold_backend):
    from consensus_specs_tpu.crypto import bls

    sks = list(range(1, 8))
    pks = [bls.SkToPk(sk) for sk in sks]
    msg = b"\x42" * 32
    v = bls.DeferredVerifier()
    with bls.deferring(v):
        # width-1 rows (Verify) and width-5 rows (FastAggregateVerify)
        for sk, pk in zip(sks[:4], pks[:4]):
            assert bls.Verify(pk, msg, bls.Sign(sk, msg))
        from consensus_specs_tpu.crypto.bls.fields import R as _R

        agg_sk = sum(sks[:5]) % _R
        assert bls.FastAggregateVerify(pks[:5], msg, bls.Sign(agg_sk, msg))
        bad = bls.Sign(agg_sk + 1, msg)
        assert bls.FastAggregateVerify(pks[:5], msg, bad)  # optimistic lie
    v.flush()
    assert v.results == [True] * 5 + [False]
    # two width buckets -> two dispatches, never cross-padded
    widths = sorted(tuple(sorted(b)) for b in fake_cold_backend.batches)
    assert widths == [(1, 1, 1, 1), (5, 5)]


def test_flush_dedups_repeated_checks(fake_cold_backend):
    from consensus_specs_tpu.crypto import bls

    sk, msg = 5, b"\x33" * 32
    pk, sig = bls.SkToPk(sk), None
    v = bls.DeferredVerifier()
    with bls.deferring(v):
        sig = bls.Sign(sk, msg)
        for _ in range(6):  # the same check recorded by six "cases"
            assert bls.Verify(pk, msg, sig)
    v.flush()
    assert v.results == [True] * 6
    assert sum(len(b) for b in fake_cold_backend.batches) == 1  # one row total


def test_flush_bucket_chaos_degrades_to_per_row(fake_cold_backend):
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.resilience import inject

    sk, msg = 9, b"\x77" * 32
    pk = bls.SkToPk(sk)
    v = bls.DeferredVerifier()
    with bls.deferring(v):
        assert bls.Verify(pk, msg, bls.Sign(sk, msg))
        assert bls.Verify(pk, msg, bls.Sign(sk + 1, msg))  # actually invalid
    with inject("sched.flush", "deterministic", count=1):
        v.flush()
    # the bucket dispatch failed; the per-row oracle path still answered
    assert v.results == [True, False]


# ---------------------------------------------------------------------------
# compile cache
# ---------------------------------------------------------------------------

def test_compile_cache_knob_resolution(monkeypatch):
    monkeypatch.delenv(compile_cache.COMPILE_CACHE_ENV, raising=False)
    monkeypatch.delenv(compile_cache.LEGACY_CACHE_ENV, raising=False)
    assert compile_cache.resolve_dir() == ""
    assert compile_cache.resolve_dir(enable_by_default=True) \
        == compile_cache.default_dir()
    monkeypatch.setenv(compile_cache.COMPILE_CACHE_ENV, "off")
    assert compile_cache.resolve_dir(enable_by_default=True) == ""
    monkeypatch.setenv(compile_cache.COMPILE_CACHE_ENV, "1")
    assert compile_cache.resolve_dir() == compile_cache.default_dir()
    monkeypatch.setenv(compile_cache.COMPILE_CACHE_ENV, "/tmp/somewhere")
    assert compile_cache.resolve_dir() == "/tmp/somewhere"
    # explicit argument beats the env
    assert compile_cache.resolve_dir("/tmp/else") == "/tmp/else"
    # legacy knob honored when the new one is unset
    monkeypatch.delenv(compile_cache.COMPILE_CACHE_ENV, raising=False)
    monkeypatch.setenv(compile_cache.LEGACY_CACHE_ENV, "/tmp/legacy")
    assert compile_cache.resolve_dir() == "/tmp/legacy"


_CACHE_CHILD = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
from consensus_specs_tpu.sched import compile_cache as cc
d = cc.configure_compile_cache({cache_dir!r}, min_compile_secs=0.0)
assert d, "cache did not configure"
import jax, jax.numpy as jnp
val = int(jax.jit(lambda x: (x * 3 + 1).sum())(jnp.arange(257)))
print(json.dumps({{"val": val, "stats": cc.compile_cache_stats()}}))
"""


def test_compile_cache_cross_process_reuse(tmp_path):
    """Two fresh processes compile the same kernel: the first misses and
    populates the cache, the second HITS — and the hit lands as a
    sched.compile_cache instant in the armed trace."""
    cache_dir = str(tmp_path / "xla-cache")
    trace_dir = tmp_path / "trace"
    env = dict(os.environ)
    env.pop("CONSENSUS_SPECS_TPU_COMPILE_CACHE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CONSENSUS_SPECS_TPU_TRACE"] = str(trace_dir)
    script = _CACHE_CHILD.format(repo=str(REPO), cache_dir=cache_dir)

    outs = []
    for _ in range(2):
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        outs.append(json.loads(proc.stdout.strip().splitlines()[-1]))

    assert outs[0]["val"] == outs[1]["val"]
    assert outs[0]["stats"]["requests"] >= 1
    assert outs[1]["stats"]["hits"] >= 1, outs
    # the hit is visible in the trace (the obs instant the report tallies)
    events = []
    for f in trace_dir.glob("spans-*.jsonl"):
        for line in f.read_text().splitlines():
            rec = json.loads(line)
            if rec.get("name") == "sched.compile_cache":
                events.append(rec["attrs"]["event"])
    assert "hit" in events and "request" in events
