"""SLO plane (obs/slo.py + tools/slo_report.py + the perfgate hook):
objective math, multi-window burn rates over ledger points, the gate's
burning / ok / environmental verdicts (chaos-drillable), and the
prometheus-scrape observation path."""
import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.obs import ledger as ledger_mod
from consensus_specs_tpu.obs import metrics, slo


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _snap(responses=0, internal=0, request_ms=()):
    metrics.reset()
    if responses:
        metrics.count("serve.responses", responses)
    if internal:
        metrics.count("serve.errors.internal", internal)
    for v in request_ms:
        metrics.observe("serve.request_ms", v)
    return metrics.snapshot()


# -- objectives + evaluation -------------------------------------------------

def test_objectives_defaults_and_env_override(monkeypatch):
    avail, latency = slo.serve_objectives()
    assert avail.target == 0.999 and avail.kind == "availability"
    assert latency.target == 25.0 and latency.kind == "latency_p99"
    monkeypatch.setenv(slo.AVAILABILITY_TARGET_ENV, "0.99")
    monkeypatch.setenv(slo.P99_OBJECTIVE_ENV, "50")
    avail, latency = slo.serve_objectives()
    assert avail.target == 0.99 and latency.target == 50.0


def test_observed_from_snapshot_excludes_4xx_from_denominator():
    snap = _snap(responses=99, internal=1, request_ms=[1.0] * 10)
    metrics.count("serve.errors.bad_request", 50)  # 4xx: never counted
    observed = slo.observed_from_snapshot(metrics.snapshot())
    assert observed["requests"] == 100
    assert observed["availability"] == 0.99
    assert observed["p99_ms"] == 1.0


def test_evaluate_budget_math():
    ok = slo.evaluate({"availability": 1.0, "p99_ms": 5.0, "requests": 10})
    by_name = {s["objective"]: s for s in ok}
    avail = by_name["serve_availability"]
    assert avail["verdict"] == slo.OK and avail["budget_remaining"] == 1.0
    lat = by_name["serve_latency_p99"]
    assert lat["verdict"] == slo.OK
    assert lat["budget_remaining"] == pytest.approx(0.8)

    burning = slo.evaluate({"availability": 0.99, "p99_ms": 50.0,
                            "requests": 100})
    by_name = {s["objective"]: s for s in burning}
    assert by_name["serve_availability"]["verdict"] == slo.BURNING
    assert by_name["serve_availability"]["burn"] == pytest.approx(10.0)
    assert by_name["serve_latency_p99"]["verdict"] == slo.BURNING
    assert by_name["serve_latency_p99"]["budget_remaining"] == pytest.approx(-1.0)

    nodata = {s["objective"]: s
              for s in slo.evaluate({"availability": None, "p99_ms": None})}
    assert all(s["verdict"] == slo.NO_DATA and not s["burning"]
               for s in nodata.values())


def test_ledger_points_shape():
    statuses = slo.evaluate({"availability": 0.9995, "p99_ms": 5.0,
                             "requests": 10})
    points = slo.ledger_points(statuses)
    assert points[slo.AVAILABILITY_POINT] == pytest.approx(0.9995)
    assert points[slo.P99_BUDGET_POINT] == pytest.approx(0.8)
    assert slo.ledger_points(slo.evaluate({"availability": None,
                                           "p99_ms": None})) == {}


# -- burn rates --------------------------------------------------------------

def test_burn_rates_multi_window():
    now = 1_000_000.0
    points = [
        # 30 min ago: a bad probe (availability 0.99 vs target 0.999)
        {"ts": now - 1800, "value": 0.99},
        # 3h ago: perfect
        {"ts": now - 3 * 3600, "value": 1.0},
        # 20h ago: perfect
        {"ts": now - 20 * 3600, "value": 1.0},
        # outside every window
        {"ts": now - 90 * 3600, "value": 0.0},
    ]
    rates = slo.burn_rates(points, target=0.999, now=now)
    assert rates["1h"]["points"] == 1
    assert rates["1h"]["burn_rate"] == pytest.approx(10.0)
    assert rates["6h"]["points"] == 2
    assert rates["6h"]["burn_rate"] == pytest.approx(5.0)
    assert rates["24h"]["points"] == 3
    assert rates["24h"]["burn_rate"] == pytest.approx(10.0 / 3, abs=1e-3)
    empty = slo.burn_rates([], target=0.999, now=now)
    assert empty["1h"]["points"] == 0 and "burn_rate" not in empty["1h"]


# -- the gate (perfgate hook) ------------------------------------------------

def test_gate_ok_burning_and_chaos_drill():
    snap = _snap(responses=200, request_ms=[1.0] * 50)
    assert slo.gate(snap)["ok"] is True

    # the CONSENSUS_SPECS_TPU_PERF_CHAOS drill shape: a factor matching
    # serve_slo_availability simulates a budget-burning daemon
    def chaos(metric):
        return 0.5 if "serve_slo_availability" in metric else 1.0

    burned = slo.gate(snap, chaos_factor=chaos)
    assert burned["ok"] is False and burned["verdict"] == slo.BURNING
    assert burned["observed"]["availability"] == 0.5
    # the latency drill: p99 inflated past the objective
    slowed = slo.gate(snap, chaos_factor=lambda m: (
        1000.0 if "serve_slo_p99_ms" in m else 1.0))
    assert slowed["ok"] is False

    # a real burn (5xx fraction above budget) with no chaos
    bad = slo.gate(_snap(responses=90, internal=10, request_ms=[1.0] * 50))
    assert bad["ok"] is False
    assert bad["points"][slo.AVAILABILITY_POINT] == pytest.approx(0.9)


def test_gate_environmental_gap_never_fails():
    # an environmentally-skipped serving slice passes with no points
    snap = _snap(responses=100, request_ms=[1.0])
    gap = slo.gate(snap, skipped_environmental=True)
    assert gap["ok"] is True and gap["verdict"] == slo.ENV_GAP
    assert gap["points"] == {}
    # zero served requests (slice never ran) is the same gap — even
    # under a chaos factor that WOULD burn a real run
    empty = slo.gate(_snap(), chaos_factor=lambda m: 0.0)
    assert empty["ok"] is True and empty["verdict"] == slo.ENV_GAP


# -- black-box observation (scraped /metrics) --------------------------------

def test_observed_from_prometheus_round_trip():
    _snap(responses=40, internal=10, request_ms=[2.0] * 90 + [80.0] * 10)
    text = metrics.prometheus_text()
    observed = slo.observed_from_prometheus(text)
    assert observed["requests"] == 50
    assert observed["availability"] == pytest.approx(0.8)
    assert observed["p99_ms"] == pytest.approx(80.0)
    assert slo.observed_from_prometheus("")["availability"] is None


# -- tools/slo_report.py -----------------------------------------------------

def _report_main(argv):
    import importlib.util
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "slo_report", repo / "tools" / "slo_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main(argv)


def test_slo_report_cold_then_banked(tmp_path, capsys):
    ledger_path = str(tmp_path / "ledger.jsonl")
    assert _report_main(["--ledger", ledger_path]) == 2  # no data at all

    led = ledger_mod.Ledger(ledger_path)
    now = time.time()
    led.record_run({slo.AVAILABILITY_POINT: 1.0, slo.P99_BUDGET_POINT: 0.9},
                   source="serve_canary", backend="host", ts=now - 600)
    led.record_run({slo.AVAILABILITY_POINT: 0.998,
                    slo.P99_BUDGET_POINT: 0.8},
                   source="perfgate", backend="host", ts=now)
    json_out = tmp_path / "slo.json"
    assert _report_main(["--ledger", ledger_path, "--json",
                         str(json_out), "--gate"]) == 1  # latest is burning
    report = json.loads(json_out.read_text())
    assert report["history"][slo.AVAILABILITY_POINT] == 2
    assert report["latest_availability"] == pytest.approx(0.998)
    assert report["burn_rates"]["1h"]["points"] == 2
    out = capsys.readouterr().out
    assert "burn" in out and "GATE FAILED" in out

    led.record_run({slo.AVAILABILITY_POINT: 1.0, slo.P99_BUDGET_POINT: 0.9},
                   source="perfgate", backend="host", ts=now + 1)
    assert _report_main(["--ledger", ledger_path, "--gate"]) == 0
