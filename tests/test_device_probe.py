"""tools/device_probe.py (ROADMAP #2): the opportunistic device probe's
degradation contract and ledger plumbing — without a device, the probe
reports an environment gap and exits 0; with a (faked) healthy device,
it banks whatever headline keys its section children produced as
backend-tagged ledger points.
"""
from __future__ import annotations

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import device_probe  # noqa: E402

from consensus_specs_tpu.obs import ledger as ledger_mod  # noqa: E402


def test_cpu_only_is_an_environment_gap(tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(device_probe, "probe_backend", lambda timeout_s: "cpu")
    out = tmp_path / "summary.json"
    rc = device_probe.main(["--ledger", str(tmp_path / "l.jsonl"),
                            "--json", str(out)])
    assert rc == 0
    assert "environment gap" in capsys.readouterr().out
    summary = json.loads(out.read_text())
    assert summary["backend"] == "cpu"
    assert "cpu-only" in summary["gap"]
    assert not (tmp_path / "l.jsonl").exists()  # nothing banked


def test_unreachable_tunnel_is_an_environment_gap(tmp_path, monkeypatch):
    monkeypatch.setattr(device_probe, "probe_backend", lambda timeout_s: None)
    rc = device_probe.main(["--ledger", str(tmp_path / "l.jsonl")])
    assert rc == 0
    assert not (tmp_path / "l.jsonl").exists()


def test_healthy_device_banks_headline_keys(tmp_path, monkeypatch):
    """A healthy (faked tpu) backend: section children report the round-4
    headline keys; the probe appends them as backend:'tpu' ledger points
    under source device_probe."""
    monkeypatch.setattr(device_probe, "probe_backend", lambda timeout_s: "tpu")

    fake_payload = {
        "block_mainnet": {"block_128atts_speedup": 3.4,
                          "block_128atts_mainnet_s": 1.2},
        "sync_aggregate": {"sync_aggregate_512_speedup": 5.1},
        "generation": {"gen_operations_speedup": 1.9},
    }
    monkeypatch.setattr(device_probe, "run_section",
                        lambda name, cap_s: fake_payload.get(name, {}))

    ledger_path = tmp_path / "ledger.jsonl"
    out = tmp_path / "summary.json"
    rc = device_probe.main(["--ledger", str(ledger_path), "--json", str(out)])
    assert rc == 0
    summary = json.loads(out.read_text())
    assert set(summary["banked"]) == {
        "block_128atts_speedup", "block_128atts_mainnet_s",
        "sync_aggregate_512_speedup", "gen_operations_speedup"}

    led = ledger_mod.Ledger(str(ledger_path))
    run = led.runs()[-1]
    assert run["source"] == "device_probe"
    assert run["backend"] == "tpu"
    points = led.series("block_128atts_speedup")
    assert points and points[-1]["value"] == 3.4


def test_healthy_device_with_dead_sections_fails(tmp_path, monkeypatch):
    monkeypatch.setattr(device_probe, "probe_backend", lambda timeout_s: "tpu")
    monkeypatch.setattr(device_probe, "run_section",
                        lambda name, cap_s: {"section_errors": {name: "rc=70"}})
    rc = device_probe.main(["--ledger", str(tmp_path / "l.jsonl")])
    assert rc == 1


def test_partial_sections_still_bank(tmp_path, monkeypatch):
    """One dead section doesn't lose the others' datapoints — the probe
    is opportunistic per key, exit 0 with a missing-keys note."""
    monkeypatch.setattr(device_probe, "probe_backend", lambda timeout_s: "tpu")
    payload = {"sync_aggregate": {"sync_aggregate_512_speedup": 4.0}}
    monkeypatch.setattr(device_probe, "run_section",
                        lambda name, cap_s: payload.get(name, {}))
    ledger_path = tmp_path / "ledger.jsonl"
    rc = device_probe.main(["--ledger", str(ledger_path)])
    assert rc == 0
    led = ledger_mod.Ledger(str(ledger_path))
    assert led.series("sync_aggregate_512_speedup")
    assert not led.series("block_128atts_speedup")


def test_probe_backend_real_subprocess():
    """The real aliveness child against this box's CPU jax: it must
    resolve a backend name without wedging (the disposable-child
    contract)."""
    backend = device_probe.probe_backend(timeout_s=120)
    assert backend == "cpu"
