"""Data-parallel suite generation drills (docs/GENPIPE.md "Sharded
generation"): the ``--workers N`` shard/merge machinery must land a
suite tree AND combined journal byte-identical to the ``--workers 1``
run — clean, after a SIGKILL'd worker (respawn resumes from the
per-rank journals), and under ``sched.worker`` chaos of both kinds
(transient = retry/respawn; deterministic = that slice degrades to the
in-process serial path). Plus the shard function's determinism
contract: any worker's slice is a pure function of (suite, N, rank)."""
from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

import pytest

from consensus_specs_tpu import resilience as r
from consensus_specs_tpu.resilience import journal as journal_mod
from consensus_specs_tpu.resilience.journal import CaseJournal
from consensus_specs_tpu.sched import shard

REPO = pathlib.Path(__file__).resolve().parent.parent
DRIVER = REPO / "tests" / "_gen_journal_driver.py"


def _run_driver(out_dir: pathlib.Path, mode, chaos: str = "",
                chaos_state: str = "") -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("CONSENSUS_SPECS_TPU_GEN_OVERLAP", None)
    env.pop("CONSENSUS_SPECS_TPU_GEN_WORKERS", None)
    if chaos:
        env[r.ENV_KNOB] = chaos
    else:
        env.pop(r.ENV_KNOB, None)
    if chaos_state:
        env["CONSENSUS_SPECS_TPU_CHAOS_STATE"] = chaos_state
    else:
        env.pop("CONSENSUS_SPECS_TPU_CHAOS_STATE", None)
    return subprocess.run(
        [sys.executable, str(DRIVER), str(out_dir)] + list(mode),
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )


def _tree(root: pathlib.Path, with_journal: bool = True) -> dict:
    skip = {"testgen_error_log.txt"}
    if not with_journal:
        skip.add(journal_mod.JOURNAL_NAME)
    return {
        str(p.relative_to(root)): p.read_bytes()
        for p in sorted(root.rglob("*"))
        if p.is_file() and p.name not in skip
    }


@pytest.fixture(scope="module")
def w1_run(tmp_path_factory):
    """The reference: ``--workers 1`` through the same shard/merge
    machinery (the acceptance baseline the merged bytes must equal)."""
    out = tmp_path_factory.mktemp("gen_shard_w1")
    proc = _run_driver(out, ["--workers", "1"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    tree = _tree(out)
    assert len(tree) >= 9
    assert journal_mod.JOURNAL_NAME in {p.split("/")[-1] for p in tree}
    return out, tree


def test_shard_rank_is_pure_and_complete():
    """Every case index lands on exactly one rank, the assignment is a
    pure function (two calls agree), and no rank starves on a stream
    longer than the worker count."""
    for workers in (1, 2, 3, 5, 8):
        seen = {rank: 0 for rank in range(workers)}
        for idx in range(4 * workers):
            rank = shard.shard_rank("operations", "phase0", idx, workers)
            assert rank == shard.shard_rank("operations", "phase0", idx, workers)
            assert 0 <= rank < workers
            seen[rank] += 1
        assert all(n == 4 for n in seen.values()), seen
    # different streams rotate their heads (the crc32 offset): not every
    # stream's case 0 may land on rank 0
    heads = {shard.shard_rank(runner, fork, 0, 4)
             for runner in ("operations", "sanity", "rewards")
             for fork in ("phase0", "altair")}
    assert len(heads) > 1


def test_workers_2_byte_identical_to_workers_1(w1_run, tmp_path):
    _, want = w1_run
    out = tmp_path / "vectors"
    proc = _run_driver(out, ["--workers", "2"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    # tree AND merged journal bytes match; no per-rank leftovers remain
    assert _tree(out) == want
    assert not list(out.glob(".gen_journal.rank*"))
    assert not list(out.glob(".gen_rank*"))


def test_sigkilled_worker_respawns_and_resumes(w1_run, tmp_path):
    """SIGKILL one worker mid-suite (cross-process-counted gen.case kill
    chaos): the parent classifies the signal death transient, respawns
    the slice, the respawn resumes from the per-rank journal, and the
    merged tree + combined journal STILL equal the --workers 1 bytes —
    all within ONE run."""
    _, want = w1_run
    out = tmp_path / "vectors"
    state = tmp_path / "chaos.state"
    proc = _run_driver(out, ["--workers", "2"],
                       chaos="gen.case=kill:1:2", chaos_state=str(state))
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-800:],
                                  proc.stderr[-800:])
    # the kill really fired (the shared state file counted its hit)...
    assert json.loads(state.read_text())["gen.case"] >= 3
    # ...and the respawned slice completed to identical bytes
    assert _tree(out) == want


def test_sched_worker_transient_chaos_retries(w1_run, tmp_path):
    _, want = w1_run
    out = tmp_path / "vectors"
    proc = _run_driver(out, ["--workers", "2"],
                       chaos="sched.worker=transient:1")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert _tree(out) == want


def test_sched_worker_deterministic_chaos_degrades_in_process(w1_run, tmp_path):
    """A deterministic sched.worker fault must NOT retry: the slice is
    degraded to the in-process serial path (visible as the [w<R>*]
    label) and the run still completes byte-identical."""
    _, want = w1_run
    out = tmp_path / "vectors"
    proc = _run_driver(out, ["--workers", "2"],
                       chaos="sched.worker=deterministic:1")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "*]" in proc.stdout  # the degraded in-process slice ran
    assert _tree(out) == want


def test_rerun_admits_from_merged_journal(w1_run, tmp_path):
    """A second --workers run over a completed tree regenerates nothing:
    every case is admitted from the merged journal the per-rank journals
    folded into."""
    _, want = w1_run
    out = tmp_path / "vectors"
    proc = _run_driver(out, ["--workers", "3"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    proc = _run_driver(out, ["--workers", "3"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "generating: " not in proc.stdout
    assert "6 skipped" in proc.stdout
    assert _tree(out) == want


def test_merge_is_completion_order_independent(tmp_path):
    """merge_journals writes sorted-case canonical bytes whatever order
    the rank journals were produced in (and tombstones invalidations)."""
    out = tmp_path
    j1 = CaseJournal(out, name=journal_mod.rank_journal_name(0))
    j2 = CaseJournal(out, name=journal_mod.rank_journal_name(1))
    case_dir = out / "z_case"
    case_dir.mkdir()
    (case_dir / "pre.yaml").write_text("a: 1\n")
    j2.record("z_case", case_dir)        # rank 1 finishes first
    (case_dir / "pre.yaml").write_text("b: 2\n")
    j1.record("a_case", case_dir)
    j1.record("dead_case", case_dir)
    j1.invalidate("dead_case")
    merged = shard.merge_journals(out, workers=2)
    assert sorted(merged) == ["a_case", "z_case"]
    lines = (out / journal_mod.JOURNAL_NAME).read_text().splitlines()
    assert [json.loads(ln)["case"] for ln in lines] == ["a_case", "z_case"]
    # idempotent: re-merging over the merged journal changes nothing
    before = (out / journal_mod.JOURNAL_NAME).read_bytes()
    shard.merge_journals(out, workers=2)
    assert (out / journal_mod.JOURNAL_NAME).read_bytes() == before
