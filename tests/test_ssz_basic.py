"""SSZ serialization/Merkleization unit tests.

Expectations are computed with an independent, naive in-test merkleizer
(plain hashlib over fully-materialized padded trees) — mirroring the
reference's hand-built ssz_generic vectors (tests/generators/ssz_generic)."""
import hashlib

import pytest

from consensus_specs_tpu import ssz
from consensus_specs_tpu.ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    Bytes32,
    Bytes48,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint8,
    uint16,
    uint32,
    uint64,
    uint256,
)


def h(x: bytes) -> bytes:
    return hashlib.sha256(x).digest()


def naive_merkleize(chunks, limit=None):
    chunks = list(chunks)
    count = len(chunks)
    if limit is None:
        limit = max(count, 1)
    size = 1
    while size < limit:
        size *= 2
    chunks = chunks + [b"\x00" * 32] * (size - count)
    while len(chunks) > 1:
        chunks = [h(chunks[i] + chunks[i + 1]) for i in range(0, len(chunks), 2)]
    return chunks[0]


def mix_len(root, n):
    return h(root + n.to_bytes(32, "little"))


# --- basic types ---

def test_uint_serialization():
    assert ssz.serialize(uint64(0x0123456789ABCDEF)) == bytes.fromhex("efcdab8967452301")
    assert ssz.serialize(uint8(5)) == b"\x05"
    assert ssz.serialize(uint16(0xABCD)) == b"\xcd\xab"
    assert uint64.decode_bytes(bytes.fromhex("efcdab8967452301")) == 0x0123456789ABCDEF
    assert ssz.serialize(uint256(1)) == b"\x01" + b"\x00" * 31


def test_uint_bounds():
    with pytest.raises(ValueError):
        uint8(256)
    with pytest.raises(ValueError):
        uint64(-1)
    with pytest.raises(ValueError):
        boolean(2)


def test_uint_root():
    assert ssz.hash_tree_root(uint64(17)) == (17).to_bytes(8, "little") + b"\x00" * 24
    assert ssz.hash_tree_root(boolean(True)) == b"\x01" + b"\x00" * 31


def test_bytes32():
    v = Bytes32(b"\x11" * 32)
    assert ssz.serialize(v) == b"\x11" * 32
    assert ssz.hash_tree_root(v) == b"\x11" * 32
    assert Bytes32() == b"\x00" * 32
    with pytest.raises(ValueError):
        Bytes32(b"\x00" * 31)
    # Bytes48 spans two chunks
    b48 = Bytes48(b"\x22" * 48)
    assert ssz.hash_tree_root(b48) == h(b"\x22" * 48 + b"\x00" * 16)


def test_bytelist():
    t = ByteList[64]
    v = t(b"abc")
    assert ssz.serialize(v) == b"abc"
    expected = mix_len(naive_merkleize([b"abc" + b"\x00" * 29], limit=2), 3)
    assert ssz.hash_tree_root(v) == expected
    assert ssz.hash_tree_root(t()) == mix_len(naive_merkleize([], limit=2), 0)


# --- bitfields (simple-serialize.md bit packing) ---

def test_bitvector():
    v = Bitvector[10]([1, 0, 1, 0, 1, 0, 1, 0, 1, 1])
    assert ssz.serialize(v) == bytes([0b01010101, 0b00000011])
    rt = Bitvector[10].decode_bytes(ssz.serialize(v))
    assert rt == v
    chunk = bytes([0b01010101, 0b00000011]) + b"\x00" * 30
    assert ssz.hash_tree_root(v) == chunk
    with pytest.raises(ValueError):
        Bitvector[10].decode_bytes(bytes([0xFF, 0xFF]))  # nonzero padding


def test_bitlist():
    v = Bitlist[8]([1, 0, 1])
    assert ssz.serialize(v) == bytes([0b1101])
    assert Bitlist[8].decode_bytes(bytes([0b1101])) == v
    chunk = bytes([0b101]) + b"\x00" * 31
    assert ssz.hash_tree_root(v) == mix_len(chunk, 3)
    # empty bitlist serializes to the lone delimiter byte
    assert ssz.serialize(Bitlist[8]([])) == b"\x01"
    with pytest.raises(ValueError):
        Bitlist[8].decode_bytes(b"")
    with pytest.raises(ValueError):
        Bitlist[8].decode_bytes(b"\x00")
    with pytest.raises(ValueError):
        Bitlist[4].decode_bytes(bytes([0b100000]))  # 5 bits > limit 4


def test_bitlist_mutation():
    v = Bitlist[16]([0] * 9)
    v[3] = True
    assert ssz.serialize(v) == bytes([0b00001000, 0b10])


# --- vectors / lists ---

def test_vector_basic():
    v = Vector[uint64, 4]([1, 2, 3, 4])
    assert ssz.serialize(v) == b"".join(i.to_bytes(8, "little") for i in [1, 2, 3, 4])
    assert ssz.hash_tree_root(v) == b"".join(i.to_bytes(8, "little") for i in [1, 2, 3, 4])
    v5 = Vector[uint64, 5]([1, 2, 3, 4, 5])
    packed = b"".join(i.to_bytes(8, "little") for i in [1, 2, 3, 4, 5]) + b"\x00" * 24
    assert ssz.hash_tree_root(v5) == naive_merkleize([packed[:32], packed[32:]], limit=2)


def test_list_basic():
    t = List[uint64, 1024]
    v = t([7, 8, 9])
    assert ssz.serialize(v) == b"".join(i.to_bytes(8, "little") for i in [7, 8, 9])
    packed = b"".join(i.to_bytes(8, "little") for i in [7, 8, 9]) + b"\x00" * 8
    # limit 1024 uint64s = 256 chunks
    assert ssz.hash_tree_root(v) == mix_len(naive_merkleize([packed], limit=256), 3)
    assert len(t.decode_bytes(ssz.serialize(v))) == 3
    v.append(10)
    assert len(v) == 4
    with pytest.raises(ValueError):
        List[uint8, 2]([1, 2, 3])


def test_huge_limit_list():
    # 2**40 limit must not materialize chunks (virtual zero padding)
    t = List[uint64, 2**40]
    root = ssz.hash_tree_root(t([1]))
    assert len(root) == 32


# --- containers ---

class Small(Container):
    a: uint64
    b: uint64


class WithVariable(Container):
    fixed: uint16
    var: List[uint8, 32]
    tail: uint16


def test_container_fixed():
    s = Small(a=1, b=2)
    assert ssz.serialize(s) == (1).to_bytes(8, "little") + (2).to_bytes(8, "little")
    expected = naive_merkleize(
        [(1).to_bytes(8, "little") + b"\x00" * 24, (2).to_bytes(8, "little") + b"\x00" * 24]
    )
    assert ssz.hash_tree_root(s) == expected
    assert Small.decode_bytes(ssz.serialize(s)) == s
    assert Small.is_fixed_byte_length()
    assert Small.type_byte_length() == 16


def test_container_variable():
    c = WithVariable(fixed=0x1234, var=List[uint8, 32]([1, 2, 3]), tail=0x5678)
    enc = ssz.serialize(c)
    # fixed(2) + offset(4) + tail(2) = 8, then var bytes
    assert enc == bytes.fromhex("3412") + (8).to_bytes(4, "little") + bytes.fromhex("7856") + bytes([1, 2, 3])
    assert WithVariable.decode_bytes(enc) == c
    assert not WithVariable.is_fixed_byte_length()


def test_container_decode_errors():
    with pytest.raises(ValueError):
        WithVariable.decode_bytes(b"\x00\x00" + (7).to_bytes(4, "little") + b"\x00\x00")  # bad first offset
    with pytest.raises(ValueError):
        Small.decode_bytes(b"\x00" * 15)


def test_container_mutation_and_copy():
    s = Small(a=1, b=2)
    s.a = 42
    assert s.a == 42
    with pytest.raises(AttributeError):
        s.c = 1
    c = s.copy()
    c.b = 99
    assert s.b == 2

    class Outer(Container):
        inner: Small

    o = Outer(inner=Small(a=5, b=6))
    o2 = o.copy()
    o2.inner.a = 50
    assert o.inner.a == 5  # deep copy


def test_container_defaults():
    s = Small()
    assert s.a == 0 and s.b == 0
    w = WithVariable()
    assert len(w.var) == 0


def test_nested_roundtrip():
    class Deep(Container):
        items: List[Small, 4]
        name: ByteList[16]
        flags: Bitlist[12]

    d = Deep(items=List[Small, 4]([Small(a=1, b=2), Small(a=3, b=4)]),
             name=ByteList[16](b"hello"),
             flags=Bitlist[12]([1, 1, 0, 1]))
    assert Deep.decode_bytes(ssz.serialize(d)) == d
    assert len(ssz.hash_tree_root(d)) == 32


# --- union ---

def test_union():
    U = Union[None, uint16, uint32]
    u = U(1, 0xAABB)
    assert ssz.serialize(u) == b"\x01\xbb\xaa"
    assert U.decode_bytes(b"\x01\xbb\xaa") == u
    assert ssz.hash_tree_root(u) == h((0xAABB).to_bytes(2, "little") + b"\x00" * 30 + (1).to_bytes(32, "little"))
    n = U(0, None)
    assert ssz.serialize(n) == b"\x00"
    assert U.decode_bytes(b"\x00") == n


# --- generalized indices ---

def test_generalized_index_container():
    gi = ssz.get_generalized_index
    # Small has 2 fields -> depth 1: a=2, b=3
    assert gi(Small, "a") == 2
    assert gi(Small, "b") == 3

    class Four(Container):
        w: uint64
        x: uint64
        y: Small
        z: uint64

    assert gi(Four, "w") == 4
    assert gi(Four, "y", "b") == 6 * 2 + 1


def test_generalized_index_list():
    t = List[Small, 8]
    gi = ssz.get_generalized_index
    # mix_in_length: data at 2, len at 3; 8 leaves under data
    assert gi(t, "__len__") == 3
    assert gi(t, 0) == 2 * 8 + 0
    assert gi(t, 5) == 2 * 8 + 5
    assert gi(t, 5, "a") == (2 * 8 + 5) * 2


def test_merkle_proof_helpers():
    leaves = [bytes([i]) * 32 for i in range(5)]
    tree = ssz.calc_merkle_tree_from_leaves(leaves, 3)
    root = tree[-1][0]
    assert root == naive_merkleize(leaves, limit=8)
    proof = ssz.get_merkle_proof(tree, 2, 3)
    assert ssz.compute_merkle_proof_root(leaves[2], proof, 2**3 + 2) == root


def test_zero_hashes():
    assert ssz.ZERO_HASHES[0] == b"\x00" * 32
    assert ssz.ZERO_HASHES[1] == h(b"\x00" * 64)
    assert ssz.ZERO_HASHES[2] == h(ssz.ZERO_HASHES[1] * 2)


def test_no_aliasing_between_parents():
    """The ownership barrier: assigning an already-owned composite into a
    second parent snapshots it, so mutating through one parent can never
    corrupt the other's value or root."""
    from consensus_specs_tpu.ssz.types import Container, List, uint64

    class Inner(Container):
        a: uint64
        b: uint64

    class Outer(Container):
        x: Inner

    inner = Inner(a=1, b=2)
    o1 = Outer(x=inner)          # fresh: adopted in place
    o2 = Outer(x=o1.x)           # owned: snapshotted
    assert o1.x is not o2.x
    r1, r2 = o1.hash_tree_root(), o2.hash_tree_root()
    assert r1 == r2
    o1.x.a = uint64(99)
    assert o2.x.a == 1           # o2 unaffected by o1's mutation
    assert o1.hash_tree_root() != r1
    assert o2.hash_tree_root() == r2

    # same barrier through list slots
    lst = List[Inner, 16]([Inner(a=7, b=8)])
    child = lst[0]
    lst2 = List[Inner, 16]([child])
    assert lst2[0] is not child
    lst.append(child)            # re-adopting into the SAME list copies too
    child.b = uint64(42)
    assert lst[1].b == 8 and lst2[0].b == 8

    # copies own their children: a copied state's child entering another
    # parent must also snapshot
    o3 = o1.copy()
    o4 = Outer(x=o3.x)
    assert o4.x is not o3.x
    o3.x.b = uint64(1234)
    assert o4.x.b != 1234

    # default-constructed children are owned too: a fresh default must
    # pass the same barrier, or sharing it into a second parent aliases
    d1 = Outer()
    d2 = Outer(x=d1.x)
    assert d2.x is not d1.x
    d1.x.a = uint64(99)
    assert d2.x.a == 0
    assert d2.hash_tree_root() == Outer().hash_tree_root()
