"""Differential cross-checks for the vectorized state-transition engine
(consensus_specs_tpu/engine/): every SoA epoch stage must produce a
bit-identical hash_tree_root post-state against the interpreted spec
oracle on randomized registries, across the production fork matrix —
host-only and fast (tier-1 CI).
"""
from __future__ import annotations

import pytest

from consensus_specs_tpu import engine
from consensus_specs_tpu.engine import backend, crosscheck
from consensus_specs_tpu.specs import build_spec

FORKS = engine.SUPPORTED_FORKS


@pytest.fixture(autouse=True)
def _interpreted_baseline():
    """Every test starts and ends with the engine uninstalled so ordering
    can't leak an installed engine into unrelated suites."""
    engine.use_interpreted_epoch()
    yield
    engine.use_interpreted_epoch()
    engine.use_backend("numpy")


@pytest.mark.parametrize("fork", FORKS)
@pytest.mark.parametrize("leak", [False, True], ids=["finalizing", "leaking"])
def test_stages_bit_identical(fork, leak):
    spec = build_spec(fork, "minimal")
    epoch = 6 if leak else 3
    for seed in (0, 1):
        state = crosscheck.random_epoch_state(
            spec, seed=seed, n_validators=64, epoch=epoch, leak=leak
        )
        for name in crosscheck.stages_for(spec):
            same, interpreted_root, vectorized_root = crosscheck.crosscheck_stage(
                spec, name, state
            )
            assert same, (
                f"{fork}/{name} diverged (seed={seed}, leak={leak}): "
                f"{interpreted_root} != {vectorized_root}"
            )


@pytest.mark.parametrize("fork", FORKS)
def test_full_epoch_with_engine_installed(fork):
    """process_epoch end-to-end: engine on == engine off, including the
    stages the engine does NOT vectorize (resets, historical roots)."""
    spec = build_spec(fork, "minimal")
    state = crosscheck.random_epoch_state(spec, seed=7, n_validators=64, epoch=6, leak=True)
    reference = state.copy()
    spec.process_epoch(reference)

    engine.use_vectorized_epoch()
    assert engine.is_vectorized()
    assert engine.stage_status(spec)["process_slashings"]
    vectorized = state.copy()
    spec.process_epoch(vectorized)

    assert bytes(reference.hash_tree_root()) == bytes(vectorized.hash_tree_root())


def test_install_is_idempotent_and_reversible():
    spec = build_spec("altair", "minimal")
    original = spec.process_slashings
    engine.use_vectorized_epoch()
    engine.use_vectorized_epoch()  # double-install must not double-wrap
    wrapped = spec.process_slashings
    assert wrapped.engine_vectorized and wrapped.__wrapped__ is original
    engine.use_interpreted_epoch()
    assert spec.process_slashings is original


def test_future_builds_get_hooked():
    engine.use_vectorized_epoch()
    spec = build_spec("bellatrix", "minimal")
    assert engine.stage_status(spec)["process_rewards_and_penalties"]
    engine.use_interpreted_epoch()
    assert not engine.stage_status(spec)["process_rewards_and_penalties"]


def test_epoch_staging_names_survive_install():
    """The test framework stages sub-transitions by fn.__name__
    (test_framework/epoch_processing.py) — wrappers must not rename."""
    from consensus_specs_tpu.test_framework.epoch_processing import get_process_calls

    spec = build_spec("altair", "minimal")
    before = get_process_calls(spec)
    engine.use_vectorized_epoch()
    assert get_process_calls(spec) == before
    engine.use_interpreted_epoch()


def test_rnd_forks_left_interpreted():
    """R&D branches may re-shape epoch processing: never auto-wrapped."""
    spec = build_spec("sharding", "minimal")
    engine.use_vectorized_epoch()
    assert not any(engine.stage_status(spec).values())
    engine.use_interpreted_epoch()


def test_jax_backend_bit_identical():
    """The opt-in jnp delta kernel must match the oracle too (CPU jax)."""
    engine.use_backend("jax")
    saved = backend.DEVICE_MIN_ROWS
    backend.DEVICE_MIN_ROWS = 1  # force dispatch on the small test registry
    try:
        spec = build_spec("altair", "minimal")
        for leak in (False, True):
            state = crosscheck.random_epoch_state(
                spec, seed=11, n_validators=64, epoch=6 if leak else 3, leak=leak
            )
            same, interpreted_root, vectorized_root = crosscheck.crosscheck_stage(
                spec, "process_rewards_and_penalties", state
            )
            assert same, f"jax backend diverged: {interpreted_root} != {vectorized_root}"
    finally:
        backend.DEVICE_MIN_ROWS = saved
        engine.use_backend("numpy")


def test_crosscheck_detects_divergence():
    """The harness itself must not be vacuous: a deliberately corrupted
    'vectorized' stage has to be flagged."""
    from consensus_specs_tpu.engine import stages

    spec = build_spec("altair", "minimal")
    state = crosscheck.random_epoch_state(spec, seed=13, n_validators=64, epoch=3)
    real = stages.vectorized_process_slashings

    def corrupted(spec_, state_):
        real(spec_, state_)
        state_.balances[0] = int(state_.balances[0]) + 1

    stages.vectorized_process_slashings = corrupted
    try:
        same, _, _ = crosscheck.crosscheck_stage(spec, "process_slashings", state)
    finally:
        stages.vectorized_process_slashings = real
    assert not same
