"""Serve fleet (ISSUE 11, docs/SERVE.md "Fleet"): consistent-hash ring
stability, idempotency-keyed failover exactly-once, the three
``serve.replica`` chaos kinds (transient kill → respawn-and-rejoin,
hang → routed around via health staleness, deterministic → quarantined
ring shrink), kill-one-replica with zero dropped requests, drain
handoff, the fleet-shared retry budget, and the
client → router → replica trace linkage."""
import json
import os
import socket
import time

import pytest

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import obs
from consensus_specs_tpu.obs import export as obs_export
from consensus_specs_tpu.obs import metrics as obs_metrics
from consensus_specs_tpu.serve import protocol
from consensus_specs_tpu.serve.client import (
    FleetClient,
    RetryBudget,
    ServeClient,
)
from consensus_specs_tpu.serve.daemon import IdemCache, ServeDaemon
from consensus_specs_tpu.serve.drill import cheap_check, victim_check
from consensus_specs_tpu.serve.fleet import FleetConfig, FleetSupervisor
from consensus_specs_tpu.serve.ring import HashRing, remap_fraction
from consensus_specs_tpu.serve.service import SpecService
from consensus_specs_tpu.serve.batcher import VerifyBatcher


# ---------------------------------------------------------------------------
# the consistent-hash ring (pure; the ≤K/N stability contract)
# ---------------------------------------------------------------------------

KEYS_1K = [f"key-{i}".encode() for i in range(1000)]


def test_ring_remove_remaps_only_victim_keys():
    """Removing one of N replicas must move EXACTLY the keys the victim
    owned (the structural consistent-hashing guarantee), which is ~K/N
    of a 1k-key sample — never a reshuffle."""
    before = HashRing(["r0", "r1", "r2", "r3"])
    owned_by_victim = {k for k in KEYS_1K if before.lookup(k) == "r1"}
    after = HashRing(["r0", "r1", "r2", "r3"])
    after.remove("r1")
    moved = {k for k in KEYS_1K if before.lookup(k) != after.lookup(k)}
    # only the victim's keys move ...
    assert moved == owned_by_victim
    # ... and that is ~K/N (generous envelope for hash variance)
    _, fraction = remap_fraction(before, after, KEYS_1K)
    assert 0.10 <= fraction <= 0.45, fraction
    # cache-affinity keys owned by survivors stay put
    for k in KEYS_1K:
        if k not in owned_by_victim:
            assert after.lookup(k) == before.lookup(k)


def test_ring_balance_and_chain():
    ring = HashRing(["r0", "r1", "r2", "r3"])
    counts = {n: 0 for n in ring.nodes()}
    for k in KEYS_1K:
        counts[ring.lookup(k)] += 1
    for n, c in counts.items():
        assert 50 <= c <= 600, (n, counts)  # no starved/hot node
    chain = ring.chain(b"some-key")
    assert chain[0] == ring.lookup(b"some-key")
    assert sorted(chain) == ["r0", "r1", "r2", "r3"]  # all, deduped


def test_ring_rejoin_restores_affinity():
    """A respawned replica rejoins under the same slot name: the
    mapping is identical to before it left — its keys come home."""
    ring = HashRing(["r0", "r1", "r2"])
    owners = {k: ring.lookup(k) for k in KEYS_1K}
    ring.remove("r1")
    ring.add("r1")
    assert {k: ring.lookup(k) for k in KEYS_1K} == owners


def test_affinity_key_strips_volatile_fields():
    check = cheap_check(7)
    base = protocol.affinity_key("verify", check)
    noisy = dict(check, idem="abc", deadline_ms=50, priority="critical",
                 trace="00-xyz-1-01", v=1)
    assert protocol.affinity_key("verify", noisy) == base
    other = protocol.affinity_key("verify", cheap_check(8))
    assert other != base
    assert protocol.affinity_key("verify_batch", check) != base


# ---------------------------------------------------------------------------
# idempotency (exactly-once per replica)
# ---------------------------------------------------------------------------

def test_idem_cache_unit():
    cache = IdemCache(capacity=2)
    assert cache.get("a") is None
    cache.put("a", 200, {"ok": True})
    cache.put("b", 400, {"ok": False})
    assert cache.get("a") == (200, {"ok": True})
    cache.put("c", 200, {"ok": True})  # evicts b (a was touched)
    assert cache.get("b") is None
    assert cache.get("a") is not None
    assert cache.stats()["size"] == 2


def test_idem_validation():
    assert protocol.request_idem({}) is None
    assert protocol.request_idem({"idem": "k1"}) == "k1"
    for bad in (7, "", "x" * 200):
        with pytest.raises(protocol.RequestError):
            protocol.request_idem({"idem": bad})


@pytest.fixture
def daemon():
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=VerifyBatcher(linger_ms=1, cache_size=0))
    d = ServeDaemon(service).start(warm=False)
    yield d
    d.drain(10)


def test_idem_replay_answers_without_reexecution(daemon):
    """A re-sent request under the same idempotency key is replayed from
    the daemon's cache: the SAME answer, no new queue admission — the
    torn-connection half of the failover exactly-once contract."""
    params = dict(cheap_check(42), idem="replay-one")
    with ServeClient(daemon.port) as c:
        first = c.call("verify", params)
        accepted = daemon.service.batcher.accepted
        again = c.call("verify", dict(params))
        assert again["valid"] == first["valid"] is False
        assert daemon.service.batcher.accepted == accepted  # no re-execution
        assert daemon.idem_cache.hits == 1
    # a deterministic 400 is settled and replays too
    bad = {"signature": "zz-not-hex", "idem": "replay-bad"}
    with ServeClient(daemon.port) as c:
        for _ in range(2):
            from consensus_specs_tpu.serve.client import ServeError

            with pytest.raises(ServeError) as err:
                c.call("verify", bad)
            assert err.value.code == protocol.BAD_REQUEST
    assert daemon.idem_cache.hits == 2


def test_heartbeat_stale_flips_readyz(daemon):
    daemon.heartbeat_stale_s = 0.2
    daemon.heartbeat()
    with ServeClient(daemon.port) as c:
        assert c.ready() is True
        time.sleep(0.35)
        assert c.ready() is False  # stale: un-routable, not dead
        assert c._roundtrip("GET", "/readyz") and True  # still answers
        daemon.heartbeat()
        assert c.ready() is True


# ---------------------------------------------------------------------------
# the forked fleet
# ---------------------------------------------------------------------------

def _mini_cfg(**overrides):
    base = dict(replicas=2, linger_ms=1.0, cache_size=0, max_batch=8,
                heartbeat_stale_s=0.5)
    base.update(overrides)
    return FleetConfig(**base)


def _drains_exactly_once(reports, allow_killed=False):
    """Every drained incarnation answered exactly what it accepted. A
    SIGKILLed incarnation (rc=-9) has no report by design — its
    unanswered work was re-sent by the routers — and is tolerated only
    where the test killed one on purpose."""
    for name, r in reports.items():
        if allow_killed and r.get("rc") == -9 and "accepted" not in r:
            continue
        assert r.get("accepted") == (r.get("flushed_rows", 0)
                                     + r.get("shed_rows", 0)), (name, r)


def test_fleet_serves_and_drain_handoff():
    """Basic fleet serving + drain handoff: SIGTERM one replica via the
    supervisor — it leaves the membership first, the router steers new
    traffic to the survivor, and its drain report proves accepted ==
    flushed + shed (nothing dropped in the handoff)."""
    sup = FleetSupervisor(_mini_cfg()).start()
    try:
        assert len(sup.members()) == 2
        with FleetClient(sup.members, retry_budget=RetryBudget(),
                         health_ttl_s=0.1, timeout_s=15) as c:
            for i in range(8):
                assert c.call("verify", cheap_check(i))["valid"] is False
            victim = sup.members()[0][0]
            report = sup.drain_replica(victim)
            assert report["rc"] == 0
            assert report["accepted"] == (report["flushed_rows"]
                                          + report["shed_rows"])
            assert [m[0] for m in sup.members()] == \
                [m for m in ("r0", "r1") if m != victim]
            for i in range(8, 16):  # survivors carry the traffic
                assert c.call("verify", cheap_check(i))["valid"] is False
    finally:
        _drains_exactly_once(sup.stop())


def test_kill_one_answered_exactly_once_fleet_wide():
    """The idempotency acceptance: a request aimed at a replica that
    dies is answered EXACTLY ONCE fleet-wide — the failover target
    executes it (one new queue admission), and a re-send of the same
    idempotency key is replayed, not re-executed."""
    sup = FleetSupervisor(_mini_cfg()).start()
    try:
        frozen = sup.members()
        victim = frozen[0][0]
        survivor_port = dict(frozen)[[n for n, _ in frozen
                                      if n != victim][0]]
        idx, check = victim_check(sup, victim, cheap_check)
        params = dict(check, idem="fleet-exactly-once")
        client = FleetClient(frozen, retry_budget=RetryBudget(),
                             health_ttl_s=3600.0, timeout_s=15)
        with ServeClient(survivor_port) as scrape, client:
            client.call("verify", cheap_check(999_999))  # warm connections
            before = scrape.health()["queue"]["accepted"]
            sup.kill_replica(victim)
            out = client.call("verify", params)
            assert out["valid"] is False
            assert client.failovers >= 1
            after = scrape.health()["queue"]["accepted"]
            assert after == before + 1  # executed once, on the survivor
            # re-send the SAME idem straight to the survivor: replayed
            replay = scrape.call("verify", dict(params))
            assert replay["valid"] is False
            assert scrape.health()["queue"]["accepted"] == after
            assert "serve_idem_hits 1" in scrape.metrics()
        # let the monitor respawn the slot so the stop() drains a live
        # fleet (the killed incarnation itself has no report by design)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(sup.members()) < 2:
            time.sleep(0.05)
    finally:
        _drains_exactly_once(sup.stop(), allow_killed=True)


def test_chaos_transient_kill_respawns_and_rejoins(monkeypatch, tmp_path):
    """serve.replica kill: ONE replica (cross-process chaos state)
    SIGKILLs itself mid-fleet; the supervisor classifies the signal
    death transient, respawns the slot, and it rejoins via /readyz —
    while the router keeps answering every request."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CHAOS", "serve.replica=kill:1")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CHAOS_STATE",
                       str(tmp_path / "chaos_state.json"))
    sup = FleetSupervisor(_mini_cfg()).start()
    try:
        with FleetClient(sup.members, retry_budget=RetryBudget(),
                         health_ttl_s=0.1, timeout_s=15) as c:
            deadline = time.monotonic() + 30
            respawned = False
            while time.monotonic() < deadline:
                assert c.call("verify",
                              cheap_check(int(time.monotonic() * 1e3) % 10**6)
                              )["valid"] is False
                reps = {r["name"]: r for r in sup.replicas()}
                if any(r["respawns"] >= 1 and r["status"] == "ready"
                       for r in reps.values()):
                    respawned = True
                    break
                time.sleep(0.05)
            assert respawned, sup.replicas()
            assert len(sup.members()) == 2  # rejoined: full strength
    finally:
        monkeypatch.delenv("CONSENSUS_SPECS_TPU_CHAOS")
        _drains_exactly_once(sup.stop())


def test_chaos_deterministic_quarantines_and_shrinks_ring(monkeypatch, tmp_path):
    """serve.replica deterministic: the faulted replica exits with a
    deterministic sysexit, the slot is QUARANTINED (never respawned),
    the ring shrinks to the survivor, and requests keep flowing."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CHAOS",
                       "serve.replica=deterministic:1")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CHAOS_STATE",
                       str(tmp_path / "chaos_state.json"))
    sup = FleetSupervisor(_mini_cfg()).start()
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            statuses = {r["name"]: r["status"] for r in sup.replicas()}
            if "quarantined" in statuses.values():
                break
            time.sleep(0.05)
        statuses = {r["name"]: r["status"] for r in sup.replicas()}
        assert "quarantined" in statuses.values(), statuses
        assert len(sup.members()) == 1  # the ring shrank
        with FleetClient(sup.members, retry_budget=RetryBudget(),
                         health_ttl_s=0.1, timeout_s=15) as c:
            for i in range(6):
                assert c.call("verify", cheap_check(i, "detq"))["valid"] is False
        health = sup.fleet_health()
        assert health["quarantined"], health
    finally:
        monkeypatch.delenv("CONSENSUS_SPECS_TPU_CHAOS")
        _drains_exactly_once(sup.stop())


def test_chaos_hang_routed_around_via_health_staleness(monkeypatch, tmp_path):
    """serve.replica hang: the replica's supervise loop stops beating,
    its /readyz flips 503 'stale' (the process is ALIVE and still
    answering HTTP), and the router steers around it — no kills, no
    errors, every request answered by the healthy sibling."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CHAOS", "serve.replica=hang:1")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CHAOS_STATE",
                       str(tmp_path / "chaos_state.json"))
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CHAOS_HANG_S", "4")
    sup = FleetSupervisor(_mini_cfg(heartbeat_stale_s=0.3)).start()
    try:
        members = sup.members()
        assert len(members) == 2
        # find the hung replica: its /readyz goes stale while its
        # process stays alive and in the supervisor's membership
        stale = None
        deadline = time.monotonic() + 10
        while stale is None and time.monotonic() < deadline:
            for name, port in members:
                with ServeClient(port, timeout_s=2) as probe:
                    status = probe._roundtrip("GET", "/readyz").get("status")
                if status == "stale":
                    stale = name
                    break
            time.sleep(0.05)
        assert stale is not None, "no replica went heartbeat-stale"
        assert len(sup.members()) == 2  # supervisor did NOT kill it
        with FleetClient(sup.members, retry_budget=RetryBudget(),
                         health_ttl_s=0.05, timeout_s=15) as c:
            for i in range(10):
                assert c.call("verify", cheap_check(i, "hang"))["valid"] is False
    finally:
        monkeypatch.delenv("CONSENSUS_SPECS_TPU_CHAOS")
        monkeypatch.delenv("CONSENSUS_SPECS_TPU_CHAOS_HANG_S")
        _drains_exactly_once(sup.stop())


def test_fleet_shared_retry_budget_gates_failover(daemon):
    """The fleet-shared token bucket: with an empty budget a failover
    re-send is refused and the transport error surfaces (the retry-storm
    guard); with budget the SAME request fails over and succeeds."""
    # a dead port: bind-then-close guarantees ECONNREFUSED
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    dead_port = sock.getsockname()[1]
    sock.close()
    members = [("dead", dead_port), ("live", daemon.port)]
    ring = HashRing(["dead", "live"])
    i = 0
    while ring.lookup(protocol.affinity_key(
            "verify", cheap_check(i, "budget"))) != "dead":
        i += 1
    check = cheap_check(i, "budget")

    empty = RetryBudget(capacity=0.0, ratio=0.0)
    with FleetClient(members, retry_budget=empty,
                     health_ttl_s=3600.0, timeout_s=5) as c:
        # defeat the first-use health probe: mark every replica fresh
        c._refresh()
        for state in c._replicas.values():
            state.ready_checked = time.monotonic()
        before = obs_metrics.snapshot()["counters"].get(
            "serve.route.budget_exhausted", 0)
        with pytest.raises(OSError):
            c.call("verify", check)
        after = obs_metrics.snapshot()["counters"].get(
            "serve.route.budget_exhausted", 0)
        assert after == before + 1

    shared = RetryBudget()  # default capacity: failover allowed
    with FleetClient(members, retry_budget=shared,
                     health_ttl_s=3600.0, timeout_s=5) as c:
        c._refresh()
        for state in c._replicas.values():
            state.ready_checked = time.monotonic()
        assert c.call("verify", check)["valid"] is False
        assert c.failovers == 1


def test_fleet_trace_links_client_router_replica(monkeypatch, tmp_path):
    """One trace id links the caller's serve.route span → its
    serve.client child → the chosen replica's serve.request span in
    ANOTHER process (remote flow arrow), per docs/OBSERVABILITY.md."""
    trace_dir = tmp_path / "trace"
    monkeypatch.setenv(obs.TRACE_ENV, str(trace_dir))
    sup = FleetSupervisor(_mini_cfg()).start()
    try:
        with FleetClient(sup.members, retry_budget=RetryBudget(),
                         health_ttl_s=0.1, timeout_s=15) as c:
            assert c.call("verify", cheap_check(3, "trace"))["valid"] is False
    finally:
        reports = sup.stop()
    _drains_exactly_once(reports)
    monkeypatch.delenv(obs.TRACE_ENV)
    records = obs_export.load_records(str(trace_dir))
    spans = [r for r in records if r.get("type") == "span"]
    routes = [s for s in spans if s["name"] == "serve.route"]
    assert routes, "no serve.route span recorded"
    route = routes[0]
    assert route["attrs"].get("replica") in ("r0", "r1")
    clients = [s for s in spans if s["name"] == "serve.client"
               and s.get("parent") == route["span"]]
    assert clients, "serve.client did not parent under serve.route"
    requests = [s for s in spans if s["name"] == "serve.request"
                and s.get("parent") in {c["span"] for c in clients}]
    assert requests, "replica serve.request did not adopt the wire context"
    req = requests[0]
    assert req.get("remote") is True  # cross-process flow arrow
    assert req["pid"] != route["pid"]  # answered in the replica process
    assert req["trace"] == route["trace"]  # ONE trace id end to end

    # tools/trace_report.py renders the per-replica fan-out from these
    import importlib.util
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "trace_report_fleet", str(repo / "tools" / "trace_report.py"))
    trace_report = importlib.util.module_from_spec(spec)
    assert spec.loader is not None
    spec.loader.exec_module(trace_report)
    summary = trace_report.summarize(records)
    fanout = summary["serve"]["route_fanout"]
    assert fanout["requests"] >= 1
    assert route["attrs"]["replica"] in fanout["by_replica"]


def test_fleet_metrics_aggregation():
    texts = [
        "serve_accepted 3\nserve_responses 5\nserve_request_ms_p99 2.5\n",
        "serve_accepted 4\nserve_responses 7\nserve_request_ms_p99 9.0\n"
        "serve_errors_internal 1\n",
    ]
    agg = obs_metrics.aggregate_prometheus(texts)
    assert agg["serve_accepted"] == 7
    assert agg["serve_responses"] == 12
    assert agg["serve_errors_internal"] == 1
    assert agg["serve_request_ms_p99"] == 9.0  # pessimistic max, not sum
