"""External known-answer vectors for the BLS stack.

Everything else in the BLS test suite is self-referential (device vs
host oracle, both same-author); these literals come from OUTSIDE the
repo, so a shared misreading of RFC 9380 or the IETF BLS draft fails
here even when the two backends agree with each other:

- RFC 9380 appendix J.10.1 — BLS12381G2_XMD:SHA-256_SSWU_RO_ suite
  (DST "QUUX-V01-CS02-with-...") final output points.
- RFC 9380 appendix K.1 — expand_message_xmd(SHA-256) uniform bytes.
- The IETF BLS-signature draft / eth2 bls conformance corpus
  (the reference generates its cases from the same three secret keys,
  /root/reference/tests/generators/bls/main.py:23-35) — SkToPk and
  Sign pinned bytes, and the G2-infinity edge-case truth table the
  reference generator encodes (main.py:40-60).

Device-backend rows are covered by running the SAME functions through
ops/bls_jax where a device is available; here the host oracle is the
subject — the existing device-parity suites (tests/test_h2c_device.py,
tests/test_bls_device.py) transfer these anchors to the device path.
"""
from __future__ import annotations

import pytest

from consensus_specs_tpu.crypto.bls import ciphersuite as cs
from consensus_specs_tpu.crypto.bls import hash_to_curve as h2c
from consensus_specs_tpu.crypto.bls.fields import Fq2

# --- RFC 9380 K.1: expand_message_xmd SHA-256, len_in_bytes = 0x20 ---------

XMD_DST = b"QUUX-V01-CS02-with-expander-SHA256-128"

XMD_VECTORS = [
    (b"", "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"),
    (b"abc", "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"),
    (b"abcdef0123456789", "eff31487c770a893cfb36f912fbfcbff40d5661771ca4b2cb4eafe524333f5c1"),
]


@pytest.mark.parametrize("msg,expect", XMD_VECTORS, ids=["empty", "abc", "abcdef"])
def test_expand_message_xmd_rfc9380(msg, expect):
    assert h2c.expand_message_xmd(msg, XMD_DST, 0x20).hex() == expect


# --- RFC 9380 J.10.1: BLS12381G2_XMD:SHA-256_SSWU_RO_ ----------------------

H2C_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"

# (msg, P.x_re, P.x_im, P.y_re, P.y_im)
H2C_VECTORS = [
    (
        b"",
        "0141ebfbdca40eb85b87142e130ab689c673cf60f1a3e98d69335266f30d9b8d4ac44c1038e9dcdd5393faf5c41fb78a",
        "05cb8437535e20ecffaef7752baddf98034139c38452458baeefab379ba13dff5bf5dd71b72418717047f5b0f37da03d",
        "0503921d7f6a12805e72940b963c0cf3471c7b2a524950ca195d11062ee75ec076daf2d4bc358c4b190c0c98064fdd92",
        "12424ac32561493f3fe3c260708a12b7c620e7be00099a974e259ddc7d1f6395c3c811cdd19f1e8dbf3e9ecfdcbab8d6",
    ),
    (
        b"abc",
        "02c2d18e033b960562aae3cab37a27ce00d80ccd5ba4b7fe0e7a210245129dbec7780ccc7954725f4168aff2787776e6",
        "139cddbccdc5e91b9623efd38c49f81a6f83f175e80b06fc374de9eb4b41dfe4ca3a230ed250fbe3a2acf73a41177fd8",
        "1787327b68159716a37440985269cf584bcb1e621d3a7202be6ea05c4cfe244aeb197642555a0645fb87bf7466b2ba48",
        "00aa65dae3c8d732d10ecd2c50f8a1baf3001578f71c694e03866e9f3d49ac1e1ce70dd94a733534f106d4cec0eddd16",
    ),
]


@pytest.mark.parametrize("msg,xr,xi,yr,yi", H2C_VECTORS, ids=["empty", "abc"])
def test_hash_to_g2_rfc9380(msg, xr, xi, yr, yi):
    p = h2c.hash_to_g2(msg, dst=H2C_DST)
    x, y = p.affine()
    assert x == Fq2(int(xr, 16), int(xi, 16))
    assert y == Fq2(int(yr, 16), int(yi, 16))


# --- IETF BLS draft / eth2 conformance corpus ------------------------------

# the three secret keys every eth2 bls conformance case is built from
SK1 = 0x263DBD792F5B1BE47ED85F8938C0F29586AF0D3AC7B977F21C278FE1462040E3
SK2 = 0x47B8192D77BF871B62E87859D653922725724A5C031AFEABC60BCEF5FF665138
SK3 = 0x328388AFF0D4A5B7DC9205ABD374E7E98F3CD9F3418EDB4EAFDA5FB16473D216

PK1 = "a491d1b0ecd9bb917989f0e74f0dea0422eac4a873e5e2644f368dffb9a6e20fd6e10c1b77654d067c0618f6e5a7f79a"
PK3 = "b53d21a4cfd562c469cc81514d4ce5a6b577d8403d32a394dc265dd190b47fa9f829fdd7963afdf972e5e77854051f6f"

MSG_AB = bytes([0xAB] * 32)


@pytest.mark.parametrize("sk,pk", [(SK1, PK1), (SK3, PK3)], ids=["sk1", "sk3"])
def test_sk_to_pk_pinned(sk, pk):
    assert cs.SkToPk(sk).hex() == pk


SIGN_VECTORS = [
    # (sk, msg, pk, signature) — BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_
    # (the corpus' sign_case for the third secret key over 0xab*32)
    (
        SK3,
        MSG_AB,
        PK3,
        "ae82747ddeefe4fd64cf9cedb9b04ae3e8a43420cd255e3c7cd06a8d88b7c7f8"
        "638543719981c5d16fa3527c468c25f0026704a6951bde891360c7e8d12ddee0"
        "559004ccdbe6046b55bae1b257ee97f7cdb955773d7cf29adf3ccbb9975e4eb9",
    ),
]


@pytest.mark.parametrize("sk,msg,pk,sig", SIGN_VECTORS, ids=["sk3_abab"])
def test_sign_pinned(sk, msg, pk, sig):
    got = cs.Sign(sk, msg)
    assert got.hex() == sig
    assert cs.Verify(bytes.fromhex(pk), msg, got)


# --- G2-infinity / degenerate edge truth table -----------------------------
# mirrors the reference generator's hand-built edge cases (bls/main.py:40-60)

G2_INF = b"\xc0" + b"\x00" * 95
G1_INF = b"\xc0" + b"\x00" * 47


def test_infinity_edge_cases():
    # aggregate of nothing is an error, not infinity
    with pytest.raises(Exception):
        cs.Aggregate([])
    # verify against the identity pubkey always fails
    assert not cs.Verify(G1_INF, MSG_AB, cs.Sign(SK1, MSG_AB))
    # the infinity signature never verifies under a real pubkey
    assert not cs.Verify(cs.SkToPk(SK1), MSG_AB, G2_INF)
    # FastAggregateVerify: no pubkeys -> False, even with the infinity sig
    assert not cs.FastAggregateVerify([], MSG_AB, G2_INF)
    # AggregateVerify: empty inputs -> False, even with the infinity sig
    assert not cs.AggregateVerify([], [], G2_INF)
    # infinity pubkey poisons an otherwise-valid fast aggregate
    assert not cs.FastAggregateVerify([cs.SkToPk(SK1), G1_INF], MSG_AB, cs.Sign(SK1, MSG_AB))
