"""Port of the reference web3-tester deposit-contract suite
(`solidity_deposit_contract/web3_tester/tests/test_deposit.py`, 194
LoC) against the executable Python model
(`consensus_specs_tpu/deposit_contract/`). The EVM/web3 stack is out
of scope for a TPU framework; the behavioral contract those tests pin
— revert conditions, event log contents, and the incremental root
matching the SSZ `List[DepositData, 2**32]` hash_tree_root — is not.
Also cross-checks the model's `abi()` fragment against the vendored
canonical ABI JSON (`solidity_deposit_contract/deposit_contract.json`).
"""
from __future__ import annotations

import json
import pathlib
from random import Random

import pytest

from consensus_specs_tpu.deposit_contract import (
    DepositContract,
    DepositContractError,
    abi,
    compute_deposit_data_root,
)
from consensus_specs_tpu.specs.build import build_spec
from consensus_specs_tpu.ssz import hash_tree_root
from consensus_specs_tpu.ssz.types import List as SSZList

GWEI = 10**9
FULL_DEPOSIT_AMOUNT = 32 * 10**9  # gwei
MIN_DEPOSIT_AMOUNT = 10**9  # gwei (1 ether on-chain minimum)

SAMPLE_PUBKEY = b"\x11" * 48
SAMPLE_WITHDRAWAL_CREDENTIALS = b"\x22" * 32
SAMPLE_VALID_SIGNATURE = b"\x33" * 96


@pytest.fixture
def spec():
    return build_spec("phase0", "minimal")


@pytest.fixture
def contract():
    return DepositContract()


def _deposit_input(spec, amount_gwei, pubkey=SAMPLE_PUBKEY,
                   withdrawal_credentials=SAMPLE_WITHDRAWAL_CREDENTIALS,
                   signature=SAMPLE_VALID_SIGNATURE):
    root = hash_tree_root(
        spec.DepositData(
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            amount=amount_gwei,
            signature=signature,
        )
    )
    return (pubkey, withdrawal_credentials, signature, bytes(root))


@pytest.mark.parametrize(
    ("success", "amount"),
    [
        (True, FULL_DEPOSIT_AMOUNT),
        (True, MIN_DEPOSIT_AMOUNT),
        (False, MIN_DEPOSIT_AMOUNT - 1),
        (True, FULL_DEPOSIT_AMOUNT + 1),
    ],
)
def test_deposit_amount(spec, contract, success, amount):
    args = _deposit_input(spec, amount)
    if success:
        assert contract.deposit(*args, value_wei=amount * GWEI)
    else:
        with pytest.raises(DepositContractError):
            contract.deposit(*args, value_wei=amount * GWEI)


@pytest.mark.parametrize(
    ("invalid_pubkey", "invalid_withdrawal_credentials", "invalid_signature", "success"),
    [
        (False, False, False, True),
        (True, False, False, False),
        (False, True, False, False),
        (False, False, True, False),
    ],
)
def test_deposit_inputs(spec, contract, invalid_pubkey,
                        invalid_withdrawal_credentials, invalid_signature, success):
    amount = FULL_DEPOSIT_AMOUNT
    pubkey = SAMPLE_PUBKEY[2:] if invalid_pubkey else SAMPLE_PUBKEY
    withdrawal_credentials = (
        SAMPLE_WITHDRAWAL_CREDENTIALS[2:]
        if invalid_withdrawal_credentials
        else SAMPLE_WITHDRAWAL_CREDENTIALS
    )
    signature = SAMPLE_VALID_SIGNATURE[2:] if invalid_signature else SAMPLE_VALID_SIGNATURE
    # the supplied root is computed over the VALID field values, as in
    # the reference harness: length validation trips first
    root = hash_tree_root(
        spec.DepositData(
            pubkey=SAMPLE_PUBKEY,
            withdrawal_credentials=SAMPLE_WITHDRAWAL_CREDENTIALS,
            amount=amount,
            signature=SAMPLE_VALID_SIGNATURE,
        )
    )
    if success:
        assert contract.deposit(pubkey, withdrawal_credentials, signature,
                                bytes(root), value_wei=amount * GWEI)
    else:
        with pytest.raises(DepositContractError):
            contract.deposit(pubkey, withdrawal_credentials, signature,
                             bytes(root), value_wei=amount * GWEI)


def test_deposit_event_log(spec, contract):
    rng = Random(42)
    amounts = [rng.randint(MIN_DEPOSIT_AMOUNT, FULL_DEPOSIT_AMOUNT * 2) for _ in range(3)]
    for i, amount in enumerate(amounts):
        args = _deposit_input(spec, amount)
        event = contract.deposit(*args, value_wei=amount * GWEI)
        assert contract.events[-1] is event
        assert event.pubkey == SAMPLE_PUBKEY
        assert event.withdrawal_credentials == SAMPLE_WITHDRAWAL_CREDENTIALS
        assert event.amount == amount.to_bytes(8, "little")
        assert event.signature == SAMPLE_VALID_SIGNATURE
        assert event.index == i.to_bytes(8, "little")


def test_deposit_tree(spec, contract):
    """10 random deposits; after each, count and root must equal the SSZ
    List[DepositData, 2**32] hash_tree_root (ref test_deposit.py:159-194)."""
    rng = Random(1)
    deposit_data_list = []
    for i in range(10):
        amount = rng.randint(MIN_DEPOSIT_AMOUNT, FULL_DEPOSIT_AMOUNT * 2)
        deposit_data = spec.DepositData(
            pubkey=SAMPLE_PUBKEY,
            withdrawal_credentials=SAMPLE_WITHDRAWAL_CREDENTIALS,
            amount=amount,
            signature=SAMPLE_VALID_SIGNATURE,
        )
        event = contract.deposit(
            SAMPLE_PUBKEY,
            SAMPLE_WITHDRAWAL_CREDENTIALS,
            SAMPLE_VALID_SIGNATURE,
            bytes(hash_tree_root(deposit_data)),
            value_wei=amount * GWEI,
        )
        deposit_data_list.append(deposit_data)
        assert event.index == i.to_bytes(8, "little")

        count = len(deposit_data_list).to_bytes(8, "little")
        assert count == contract.get_deposit_count()
        root = hash_tree_root(SSZList[spec.DepositData, 2**32](deposit_data_list))
        assert bytes(root) == contract.get_deposit_root()


def test_deposit_data_root_matches_ssz(spec):
    """compute_deposit_data_root (the contract's in-line SSZ
    reconstruction) must equal the library hash_tree_root."""
    for amount in (MIN_DEPOSIT_AMOUNT, FULL_DEPOSIT_AMOUNT, FULL_DEPOSIT_AMOUNT * 2 + 1):
        expected = hash_tree_root(
            spec.DepositData(
                pubkey=SAMPLE_PUBKEY,
                withdrawal_credentials=SAMPLE_WITHDRAWAL_CREDENTIALS,
                amount=amount,
                signature=SAMPLE_VALID_SIGNATURE,
            )
        )
        got = compute_deposit_data_root(
            SAMPLE_PUBKEY, SAMPLE_WITHDRAWAL_CREDENTIALS, amount, SAMPLE_VALID_SIGNATURE
        )
        assert got == bytes(expected)


def test_model_abi_matches_vendored_artifact():
    """Every function/event the model's abi() declares must appear in
    the canonical vendored ABI with identical input/output types."""
    artifact = json.loads(
        (pathlib.Path(__file__).parent.parent / "solidity_deposit_contract"
         / "deposit_contract.json").read_text()
    )["abi"]

    def shape(entry):
        return (
            entry.get("name"),
            entry.get("type"),
            tuple((i.get("name"), i.get("type")) for i in entry.get("inputs", [])),
            tuple(o.get("type") for o in entry.get("outputs", [])),
        )

    canonical = {shape(e) for e in artifact}
    for entry in abi():
        assert shape(entry) in canonical, f"model ABI entry not canonical: {entry}"
