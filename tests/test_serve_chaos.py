"""Chaos coverage for the serving plane (ISSUE 6 satellite): a backend
fault injected mid-flight at the ``serve.flush`` site degrades THAT
batch to the host oracle while concurrent clients still get correct
(bit-identical) answers; a ``serve.request`` fault surfaces as a
structured 500 and the daemon keeps serving; a full queue produces
counted 429s, not hangs."""
import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import obs, resilience
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.serve import (
    ServeClient,
    ServeDaemon,
    ServeError,
    SpecService,
    VerifyBatcher,
)
from consensus_specs_tpu.serve.protocol import to_hex


@pytest.fixture()
def daemon():
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=VerifyBatcher(linger_ms=120, cache_size=0))
    d = ServeDaemon(service).start(warm=False)
    yield d
    d.drain(10)


@pytest.fixture(scope="module")
def checks():
    from consensus_specs_tpu.crypto.bls import ciphersuite as oracle
    from consensus_specs_tpu.crypto.bls.fields import R

    sks = [41, 42]
    pks = [oracle.SkToPk(sk) for sk in sks]
    msg = b"\x5d" * 32
    sig = oracle.Sign(sum(sks) % R, msg)
    return pks, msg, sig


def test_midflight_backend_fault_degrades_batch_to_oracle(daemon, checks):
    """Four concurrent clients land in one linger window; the flush they
    share is chaos-faulted. The batch must degrade to the host oracle:
    every client still gets the answer the direct path computes, the
    degradation is counted, and the NEXT flush is clean."""
    pks, msg, sig = checks
    direct = {
        "valid": bls.FastAggregateVerify(pks, msg, sig),
        "tampered": bls.FastAggregateVerify(pks, b"\x5e" * 32, sig),
    }
    assert direct == {"valid": True, "tampered": False}

    answers = {}
    errors = []

    def worker(name, message):
        try:
            with ServeClient(daemon.port) as c:
                answers[name] = c.verify(pubkeys=pks, message=message,
                                         signature=sig)
        except Exception as e:  # a dropped/errored request fails the drill
            errors.append(f"{name}: {e}")

    with resilience.inject("serve.flush", "deterministic", count=1):
        threads = [
            threading.Thread(target=worker, args=(f"valid{i}", msg))
            for i in range(3)
        ] + [threading.Thread(target=worker, args=("tampered", b"\x5e" * 32))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)

    assert not errors, errors
    assert answers == {"valid0": True, "valid1": True, "valid2": True,
                       "tampered": False}
    snap = obs.snapshot()
    assert snap["counters"].get("serve.flush_degraded", 0) >= 1
    fallbacks = [e for e in resilience.events()
                 if e["event"] == "fallback" and e["domain"] == "serve.flush"]
    assert fallbacks, "degradation must be a recorded resilience event"

    # the breaker did NOT open for the serve plane: the next request
    # flushes normally (fault was injected, not systemic)
    with ServeClient(daemon.port) as c:
        assert c.verify(pubkeys=pks, message=msg, signature=sig) is True


def test_request_fault_is_structured_500_and_daemon_survives(daemon):
    with ServeClient(daemon.port) as c:
        with resilience.inject("serve.request", "deterministic", count=1):
            with pytest.raises(ServeError) as e:
                c.call("hash_tree_root", {"fork": "phase0",
                                          "preset": "minimal",
                                          "type": "Fork", "ssz": "0x" + "00" * 16})
        assert e.value.status == 500 and e.value.code == "internal"
        assert "deterministic" in e.value.message
        # same request, chaos disarmed: the daemon still serves
        spec = daemon.service._matrix[("phase0", "minimal")]
        ssz = spec.Fork().encode_bytes()
        assert c.hash_tree_root("phase0", "minimal", "Fork", ssz) \
            == bytes(spec.Fork().hash_tree_root())


def test_queue_full_is_counted_429(daemon, checks):
    """Admission control over the wire: with a 1-slot queue and a held
    flusher window, the second concurrent distinct check is rejected as
    a structured 429 and counted — never queued unbounded, never hung."""
    pks, msg, sig = checks
    b = daemon.service.batcher
    b.max_queue = 1
    try:
        statuses = {}

        def worker(i):
            try:
                with ServeClient(daemon.port) as c:
                    c.verify(pubkeys=pks,
                             message=bytes([i]) * 32, signature=sig)
                statuses[i] = 200
            except ServeError as e:
                statuses[i] = e.status

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert sorted(statuses.values()).count(429) >= 1
        assert 200 in statuses.values()
        assert b.rejected >= 1
        with ServeClient(daemon.port) as c:
            assert c.health()["queue"]["rejected"] >= 1
    finally:
        b.max_queue = 1024
