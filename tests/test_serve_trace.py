"""ISSUE 7 tentpole acceptance: cross-wire trace linkage. A client with
tracing armed drives an in-process daemon; the exported trace.json must
contain the daemon-side request span parented under the client's
request span (same trace id), a synthesized queue-wait child, and the
shared flush span linked to the member request — including under a
chaos-degraded flush — with flow arrows in the Chrome export, and
``/debug/requests`` must return the same request by trace id."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import obs, resilience
from consensus_specs_tpu.obs import flightrec
from consensus_specs_tpu.obs.core import parse_traceparent
from consensus_specs_tpu.serve import (
    ServeClient,
    ServeDaemon,
    ServeError,
    SpecService,
    VerifyBatcher,
)


@pytest.fixture(scope="module")
def daemon():
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=VerifyBatcher(linger_ms=2))
    d = ServeDaemon(service).start(warm=False)
    yield d
    d.drain(10)


@pytest.fixture(scope="module")
def checks():
    from consensus_specs_tpu.crypto.bls import ciphersuite as oracle
    from consensus_specs_tpu.crypto.bls.fields import R

    sks = [51, 52]
    pks = [oracle.SkToPk(sk) for sk in sks]
    msg = b"\x5a" * 32
    sig = oracle.Sign(sum(sks) % R, msg)
    return pks, msg, sig


@pytest.fixture()
def trace_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.TRACE_ENV, str(tmp_path))
    flightrec.RECORDER.clear()
    yield tmp_path


def _spans(trace_dir):
    return [r for r in obs.read_records(str(trace_dir))
            if r.get("type") == "span"]


def _span_map(spans):
    return {s["name"]: s for s in spans}


# -- traceparent helpers -----------------------------------------------------

def test_traceparent_round_trip(trace_dir):
    with obs.span("client.root") as sp:
        tp = obs.traceparent()
        assert tp is not None and tp.startswith("00-") and tp.endswith("-01")
        parsed = parse_traceparent(tp)
        assert parsed is not None
        assert parsed["parent_id"] == sp.span_id
        # the zfilled 32-char trace field recovers the native 16-char id
        assert len(parsed["trace_id"]) == 16
        assert tp.split("-")[1].endswith(parsed["trace_id"].lstrip("0") or "0")


def test_traceparent_none_without_span_or_tracing(trace_dir, monkeypatch):
    assert obs.traceparent() is None  # armed, but no open span
    monkeypatch.delenv(obs.TRACE_ENV)
    assert obs.traceparent() is None  # disarmed


@pytest.mark.parametrize("bad", [
    None, 7, "", "garbage", "01-aa-bb-01", "00-zz-bb",  # wrong shape
    "00-" + "0" * 32 + "-x-01",                          # all-zero trace
])
def test_parse_traceparent_rejects_malformed(bad):
    assert parse_traceparent(bad) is None


# -- the linkage drill -------------------------------------------------------

def _drive_and_export(daemon, trace_dir, checks, message=None):
    pks, msg, sig = checks
    with obs.span("drill.root"):
        with ServeClient(daemon.port) as client:
            assert client.verify(pubkeys=pks, message=message or msg,
                                 signature=sig) in (True, False)
    spans = _spans(trace_dir)
    by_name = _span_map(spans)
    for required in ("drill.root", "serve.client", "serve.request",
                     "serve.queue_wait", "serve.flush"):
        assert required in by_name, \
            f"{required} missing from {sorted(by_name)}"
    return spans, by_name


def test_cross_wire_linkage(daemon, trace_dir, checks):
    spans, by_name = _drive_and_export(daemon, trace_dir, checks)
    client_span = by_name["serve.client"]
    request = by_name["serve.request"]
    queue_wait = by_name["serve.queue_wait"]
    flush = by_name["serve.flush"]

    # daemon request adopts the client's context: parent AND trace id
    assert request["parent"] == client_span["span"]
    assert request["trace"] == client_span["trace"]
    assert request.get("remote") is True
    # the synthesized queue-wait child hangs under the daemon request
    assert queue_wait["parent"] == request["span"]
    assert queue_wait["trace"] == client_span["trace"]
    # the shared flush links the member request and names its trace
    assert request["span"] in flush.get("links", [])
    assert client_span["trace"] in str(flush["attrs"].get("client_traces"))

    # the Chrome export draws the flow arrows (client->daemon + link)
    path = obs.export_chrome(str(trace_dir))
    with open(path) as f:
        trace = json.load(f)
    ok, why = obs.validate_chrome(trace)
    assert ok, why
    flows = [e for e in trace["traceEvents"] if e.get("ph") in ("s", "f")]
    assert {e["name"] for e in flows} >= {"spawn", "link"}
    # round trip: links survive trace.json -> records
    rt = obs.records_from_chrome(trace)
    rt_flush = [r for r in rt if r["name"] == "serve.flush"][0]
    assert request["span"] in rt_flush.get("links", [])


def test_debug_requests_returns_same_request_by_trace_id(daemon, trace_dir,
                                                         checks):
    # a fresh message: a result-cache hit would answer without a flush
    spans, by_name = _drive_and_export(daemon, trace_dir, checks,
                                       message=b"\x5d" * 32)
    trace_id = by_name["serve.client"]["trace"]
    with ServeClient(daemon.port) as client:
        out = client._roundtrip("GET", f"/debug/requests?trace={trace_id}")
    assert out["requests"], f"no flight-recorder entry for trace {trace_id}"
    entry = out["requests"][0]
    assert entry["trace"] == trace_id
    assert entry["method"] == "verify"
    assert entry["span"] == by_name["serve.request"]["span"]
    assert entry["status"] == "ok"
    assert entry["queue_wait_ms"] >= 0 and entry["flush_ms"] >= 0
    assert entry["batch_rows"] >= 1


def test_linkage_survives_chaos_degraded_flush(daemon, trace_dir, checks):
    pks, msg, sig = checks
    tampered = b"\x5b" * 32
    with resilience.inject("serve.flush", "deterministic", count=1):
        spans, by_name = _drive_and_export(daemon, trace_dir, checks,
                                           message=tampered)
    request = by_name["serve.request"]
    flush = by_name["serve.flush"]
    assert request["parent"] == by_name["serve.client"]["span"]
    assert request["span"] in flush.get("links", [])
    # the degradation is visible on the SAME request: resilience instant
    # in the trace + degraded flag in the flight recorder
    instants = [r for r in obs.read_records(str(trace_dir))
                if r.get("type") == "instant"
                and str(r.get("name", "")).startswith("resilience.")]
    assert instants, "chaos-degraded flush left no resilience instant"
    entry = flightrec.requests(trace=request["trace"])[0]
    assert entry.get("degraded") is True
    assert entry["status"] == "ok"  # degraded, still answered correctly


# -- v1 compatibility: the trace field is optional ---------------------------

def test_untraced_client_and_malformed_trace_are_served(daemon, checks):
    pks, msg, sig = checks
    with ServeClient(daemon.port) as client:
        # no tracing armed: no trace field, served as before
        assert client.verify(pubkeys=pks, message=msg, signature=sig) is True
        # malformed traceparent STRING: ignored (trace restarts), served
        from consensus_specs_tpu.serve.protocol import to_hex

        out = client.call("verify", {
            "pubkeys": [to_hex(p) for p in pks], "message": to_hex(msg),
            "signature": to_hex(sig), "trace": "not-a-traceparent"})
        assert out["valid"] is True
        # non-string trace: a typed contract violation -> 400
        with pytest.raises(ServeError) as e:
            client.call("verify", {
                "pubkeys": [to_hex(p) for p in pks], "message": to_hex(msg),
                "signature": to_hex(sig), "trace": 12345})
        assert e.value.status == 400
