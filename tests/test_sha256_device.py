"""JAX batched SHA-256 vs hashlib, and backend swap equivalence."""
import hashlib
import os
import random

import pytest

from consensus_specs_tpu.ops import sha256 as dev
from consensus_specs_tpu.ssz import hashing, merkleize_chunks


def test_single_block():
    data = bytes(range(64))
    assert dev.hash_many_device(data) == hashlib.sha256(data).digest()


def test_batch_blocks():
    rng = random.Random(1234)
    blocks = [bytes(rng.randrange(256) for _ in range(64)) for _ in range(37)]
    got = dev.hash_many_device(b"".join(blocks))
    want = b"".join(hashlib.sha256(b).digest() for b in blocks)
    assert got == want


def test_merkle_root_device_matches_host():
    rng = random.Random(7)
    for n, limit in [(1, 1), (3, 8), (8, 8), (5, 2**32), (0, 16)]:
        chunks = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(n)]
        host = merkleize_chunks(chunks, limit=limit)
        devr = dev.merkle_root_device(b"".join(chunks), limit=limit)
        assert devr == host, (n, limit)


def test_backend_swap():
    rng = random.Random(99)
    chunks = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(11)]
    host_root = merkleize_chunks(chunks, limit=16)
    dev.use_device_hasher(calibrate=False)
    try:
        assert hashing.backend_name() == "jax"
        # force the device path even for tiny batches so the equivalence
        # assertion actually exercises the jax backend
        hashing.DEVICE_MIN_BLOCKS = 0
        hashing.FUSED_ROOT_MIN_CHUNKS = 2
        assert merkleize_chunks(chunks, limit=16) == host_root
    finally:
        dev.use_host_hasher()
    assert hashing.backend_name() == "hashlib"


def test_backend_swap_large_batch():
    """A >=DEVICE_MIN_BLOCKS batch goes through the device hash_many path
    with default thresholds."""
    rng = random.Random(5)
    chunks = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(512)]
    host_root = merkleize_chunks(chunks, limit=512)
    dev.use_device_hasher(calibrate=False)
    try:
        assert merkleize_chunks(chunks, limit=512) == host_root
    finally:
        dev.use_host_hasher()


def test_tree_levels_and_item_roots_device():
    rng = random.Random(31)
    leaves = bytes(rng.randrange(256) for _ in range(32 * 24))
    got = dev.tree_levels_device(leaves)
    # oracle: host level-by-level with pow2 zero-padding
    from consensus_specs_tpu.ssz.merkle import next_pow2

    size = next_pow2(24)
    padded = leaves + b"\x00" * ((size - 24) * 32)
    want = []
    nodes = padded
    while len(nodes) > 32:
        nodes = b"".join(
            hashlib.sha256(nodes[64 * i : 64 * i + 64]).digest() for i in range(len(nodes) // 64)
        )
        want.append(nodes)
    assert got == want

    packed = bytes(rng.randrange(256) for _ in range(32 * 8 * 10))  # 10 items, 8 chunks
    roots = dev.item_roots_device(packed, 8)
    for i in range(10):
        item = packed[32 * 8 * i : 32 * 8 * (i + 1)]
        nodes = item
        while len(nodes) > 32:
            nodes = b"".join(
                hashlib.sha256(nodes[64 * j : 64 * j + 64]).digest() for j in range(len(nodes) // 64)
            )
        assert roots[32 * i : 32 * i + 32] == nodes, i
