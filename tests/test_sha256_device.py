"""JAX batched SHA-256 vs hashlib, and backend swap equivalence."""
import hashlib
import os
import random

import pytest

from consensus_specs_tpu.ops import sha256 as dev
from consensus_specs_tpu.ssz import hashing, merkleize_chunks


def test_single_block():
    data = bytes(range(64))
    assert dev.hash_many_device(data) == hashlib.sha256(data).digest()


def test_batch_blocks():
    rng = random.Random(1234)
    blocks = [bytes(rng.randrange(256) for _ in range(64)) for _ in range(37)]
    got = dev.hash_many_device(b"".join(blocks))
    want = b"".join(hashlib.sha256(b).digest() for b in blocks)
    assert got == want


def test_merkle_root_device_matches_host():
    rng = random.Random(7)
    for n, limit in [(1, 1), (3, 8), (8, 8), (5, 2**32), (0, 16)]:
        chunks = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(n)]
        host = merkleize_chunks(chunks, limit=limit)
        devr = dev.merkle_root_device(b"".join(chunks), limit=limit)
        assert devr == host, (n, limit)


def test_backend_swap():
    rng = random.Random(99)
    chunks = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(11)]
    host_root = merkleize_chunks(chunks, limit=16)
    dev.use_device_hasher()
    try:
        assert hashing.backend_name() == "jax"
        assert merkleize_chunks(chunks, limit=16) == host_root
    finally:
        dev.use_host_hasher()
    assert hashing.backend_name() == "hashlib"
