"""Overload control for the serving plane (ISSUE 10, docs/SERVE.md
"Overload control"): deadline admission + in-queue expiry shedding,
the AIMD adaptive queue limit, priority classes (sheddable shed first,
critical bypasses), brownout, the supervised admission controller with
its ``serve.admission`` chaos site (a HUNG admission check must trip
supervision, never wedge the accept loop), and the wire surface
(``deadline_ms`` / ``priority`` fields, ``deadline_exceeded`` /
``shed`` error codes, ``/debug/overload``, ``serve.shed.*`` counters in
``prometheus_text``)."""
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import obs, resilience
from consensus_specs_tpu.obs import flightrec
from consensus_specs_tpu.serve import protocol
from consensus_specs_tpu.serve.admission import (
    AdmissionController,
    AimdLimit,
    WaitEstimator,
)
from consensus_specs_tpu.serve.batcher import (
    DeadlineExceeded,
    QueueFull,
    Shed,
    VerifyBatcher,
)


def garbage_check(i: int):
    """Well-formed but invalid key: the oracle answers False without
    pairing cost (same shape as test_serve_batcher)."""
    return ("fav", (bytes([i % 251 + 1]) * 48,), i.to_bytes(4, "big") * 8,
            b"\x02" * 96)


class _StubAdmission:
    """A deterministic controller stand-in for batcher admission-logic
    units: fixed published limit/brownout, a real estimator."""

    def __init__(self, limit: int, brownout: bool = False) -> None:
        self._limit = limit
        self._brownout = brownout
        self.estimator = WaitEstimator()

    def start(self):
        return self

    def stop(self, timeout_s: float = 2.0) -> None:
        pass

    def limit(self) -> int:
        return self._limit

    def brownout(self) -> bool:
        return self._brownout

    def snapshot(self):
        return {"mode": "stub", "limit": self._limit,
                "brownout": self._brownout,
                "estimator": self.estimator.snapshot()}


# ---------------------------------------------------------------------------
# estimator + AIMD units
# ---------------------------------------------------------------------------

def test_estimator_cold_start_is_optimistic():
    est = WaitEstimator()
    assert est.estimate_ms(0) == 0.0
    assert est.estimate_ms(100) == 0.0  # no evidence -> never rejects


def test_estimator_forward_model_scales_with_depth():
    est = WaitEstimator()
    est.note_flush(rows=4, service_s=0.1)  # 40 rows/s drain rate
    assert est.drain_rate() == pytest.approx(40.0)
    assert est.estimate_ms(40) == pytest.approx(1000.0)
    assert est.estimate_ms(4) == pytest.approx(100.0)
    # recent waits act as a floor when they exceed the forward model
    for _ in range(20):
        est.observe_wait(500.0)
    assert est.estimate_ms(4) == pytest.approx(500.0)
    # empty queue estimates zero wait regardless of history
    assert est.estimate_ms(0) == 0.0


def test_aimd_limit_decreases_multiplicatively_and_recovers():
    aimd = AimdLimit(hard_limit=1024, min_limit=16, target_p99_ms=50.0)
    assert aimd.limit == 1024
    aimd.update(200.0)  # over target -> x0.65
    assert aimd.limit == int(1024 * 0.65)
    for _ in range(100):
        aimd.update(1e9)
    assert aimd.limit == 16  # clamped at the floor
    aimd.update(None)  # no evidence reads as calm -> additive increase
    assert aimd.limit == 24
    for _ in range(1000):
        aimd.update(1.0)
    assert aimd.limit == 1024  # clamped at the hard bound


# ---------------------------------------------------------------------------
# batcher admission: deadlines
# ---------------------------------------------------------------------------

def test_expired_deadline_is_shed_before_flush_work():
    """An entry whose deadline passes while queued is answered
    deadline_exceeded when its batch pops — before any dispatch — and
    the exactly-once accounting books it as a shed, not a flush."""
    b = VerifyBatcher(linger_ms=60_000, cache_size=0).start()
    results = {}

    def worker(name, deadline_ms):
        try:
            results[name] = b.submit(garbage_check(ord(name[0])),
                                     timeout_s=30, deadline_ms=deadline_ms)
        except BaseException as e:
            results[name] = e

    threads = [threading.Thread(target=worker, args=("dead", 50.0)),
               threading.Thread(target=worker, args=("live", None))]
    for t in threads:
        t.start()
    while b.depth() < 2:
        time.sleep(0.005)
    time.sleep(0.12)  # the 50ms budget expires in-queue
    assert b.drain(15) is True
    for t in threads:
        t.join(15)
    assert isinstance(results["dead"], DeadlineExceeded)
    assert results["live"] is False  # garbage check, answered normally
    assert b.accepted == 2
    assert b.flushed_rows == 1 and b.shed_rows == 1
    assert b.shed_by_class["deadline"] == 1
    assert b.accepted == b.flushed_rows + b.shed_rows


def test_admission_rejects_predicted_late_request():
    """Evidence of a slow drain + deep queue must reject a tight
    deadline at admission (never queued, not counted accepted)."""
    b = VerifyBatcher(cache_size=0, admission=_StubAdmission(limit=1024))
    # 10 rows/s drain; 5 queued rows ahead -> ~500ms estimated wait
    b.admission.estimator.note_flush(rows=1, service_s=0.1)
    b._enqueue([garbage_check(i) for i in range(5)])
    with pytest.raises(DeadlineExceeded):
        b._enqueue([garbage_check(99)], deadline_ms=100.0)
    assert b.accepted == 5  # the reject was never admitted
    assert b.shed_by_class["admission_deadline"] == 1
    assert b.shed_rows == 0  # admission-time refusals are not queued sheds
    # a generous budget still gets in
    b._enqueue([garbage_check(100)], deadline_ms=10_000.0)
    assert b.accepted == 6


# ---------------------------------------------------------------------------
# batcher admission: priority classes
# ---------------------------------------------------------------------------

def test_sheddable_is_refused_over_the_adaptive_limit():
    b = VerifyBatcher(cache_size=0, admission=_StubAdmission(limit=4))
    b._enqueue([garbage_check(i) for i in range(4)])
    with pytest.raises(Shed):
        b._enqueue([garbage_check(9)],
                   priority=protocol.PRIORITY_SHEDDABLE)
    assert b.shed_by_class["priority"] == 1
    assert b.depth() == 4


def test_default_traffic_evicts_queued_sheddable():
    """Over the adaptive limit, queued sheddable entries are evicted
    (answered Shed) to make room for default traffic — shed the low
    class first, exactly-once accounting intact."""
    b = VerifyBatcher(cache_size=0, admission=_StubAdmission(limit=4))
    shed_results = {}

    def shed_worker(i):
        try:
            shed_results[i] = b.submit(garbage_check(i), timeout_s=10,
                                       priority=protocol.PRIORITY_SHEDDABLE)
        except BaseException as e:
            shed_results[i] = e

    threads = [threading.Thread(target=shed_worker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    while b.depth() < 2:
        time.sleep(0.005)
    b._enqueue([garbage_check(10), garbage_check(11)])  # fills to limit 4
    pendings = b._enqueue([garbage_check(12)])  # over limit -> evicts 1
    for t in threads:
        t.join(10)
    evicted = [r for r in shed_results.values() if isinstance(r, Shed)]
    assert len(evicted) == 1, f"exactly one eviction expected: {shed_results}"
    assert b.depth() == 4  # still at the limit
    assert pendings[0] in b._q
    assert b.shed_rows == 1  # the evicted entry WAS accepted -> a queued shed


def test_critical_bypasses_adaptive_limit_but_not_hard_bound():
    b = VerifyBatcher(max_queue=6, cache_size=0,
                      admission=_StubAdmission(limit=2))
    b._enqueue([garbage_check(i) for i in range(2)])
    with pytest.raises(QueueFull):
        b._enqueue([garbage_check(8)])  # default: no sheddables to evict
    b._enqueue([garbage_check(9)], priority=protocol.PRIORITY_CRITICAL)
    b._enqueue([garbage_check(10), garbage_check(11), garbage_check(12)],
               priority=protocol.PRIORITY_CRITICAL)
    assert b.depth() == 6  # critical rode past the adaptive limit...
    with pytest.raises(QueueFull):
        b._enqueue([garbage_check(13)],
                   priority=protocol.PRIORITY_CRITICAL)  # ...never the hard one


def test_brownout_collapses_linger_window():
    calm = VerifyBatcher(linger_ms=25, admission=_StubAdmission(limit=8))
    assert calm._effective_linger_s() == pytest.approx(0.025)
    browned = VerifyBatcher(linger_ms=25,
                            admission=_StubAdmission(limit=8, brownout=True))
    assert browned._effective_linger_s() == 0.0


# ---------------------------------------------------------------------------
# the admission controller under chaos (site serve.admission)
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _clean_admission_breaker():
    yield
    resilience.clear(AdmissionController.CAPABILITY)
    resilience.disarm()


def test_controller_ticks_and_publishes():
    c = AdmissionController(256, mode="adaptive", tick_s=0.01,
                            stale_s=5.0).start()
    try:
        deadline = time.monotonic() + 5
        while c._ticks < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert c._ticks >= 3
        assert c.adaptive and c.limit() == 256  # calm -> stays at the cap
        snap = c.snapshot()
        assert snap["mode"] == "adaptive" and snap["degraded"] is None
    finally:
        c.stop()


def test_transient_admission_fault_is_retried_not_degraded():
    c = AdmissionController(256, mode="adaptive", tick_s=0.01, stale_s=5.0)
    with resilience.inject("serve.admission", "transient", count=1):
        c.start()
        deadline = time.monotonic() + 5
        while c._ticks < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    try:
        assert c._ticks >= 3 and c.adaptive
        assert not resilience.is_quarantined(c.CAPABILITY)
    finally:
        c.stop()


def test_deterministic_admission_fault_quarantines_and_degrades():
    c = AdmissionController(256, mode="adaptive", tick_s=0.01, stale_s=5.0)
    with resilience.inject("serve.admission", "deterministic", count=1):
        c.start()
        deadline = time.monotonic() + 5
        while c._degraded is None and time.monotonic() < deadline:
            time.sleep(0.01)
    try:
        assert c._degraded is not None
        assert resilience.is_quarantined(c.CAPABILITY)
        assert c.limit() == 256  # the fixed bound takes over
        assert not c.brownout()
    finally:
        c.stop()


def test_hung_admission_check_trips_supervision_not_the_accept_loop():
    """The satellite drill: chaos kind ``hang`` wedges the controller
    tick. The accept path must keep admitting at the fixed bound — a
    submit never blocks on the controller — and the staleness watchdog
    must quarantine serve.admission with a recorded event."""
    os.environ["CONSENSUS_SPECS_TPU_CHAOS_HANG_S"] = "30"
    try:
        c = AdmissionController(64, mode="adaptive", tick_s=0.01,
                                stale_s=0.15)
        b = VerifyBatcher(max_queue=64, linger_ms=1, cache_size=0,
                          admission=c)
        with resilience.inject("serve.admission", "hang", count=1):
            b.start()
            time.sleep(0.4)  # hang fires on an early tick; staleness > 0.15s
            t0 = time.monotonic()
            assert b.submit(garbage_check(1), timeout_s=10) is False
            assert time.monotonic() - t0 < 5  # the accept loop never wedged
        assert c._degraded is not None, "staleness watchdog did not trip"
        assert resilience.is_quarantined(c.CAPABILITY)
        events = [e for e in resilience.events()
                  if e["event"] == "quarantine"
                  and e["capability"] == c.CAPABILITY]
        assert events, "the hung admission check must be a recorded event"
        assert c.limit() == 64  # degraded to the fixed bound
        assert b.drain(10) is True
    finally:
        os.environ.pop("CONSENSUS_SPECS_TPU_CHAOS_HANG_S", None)


# ---------------------------------------------------------------------------
# the wire surface (in-process daemon)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def wire_daemon():
    from consensus_specs_tpu.serve import ServeDaemon, SpecService

    flightrec.RECORDER.clear()
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=VerifyBatcher(linger_ms=1, cache_size=0),
                          request_timeout_s=30)
    d = ServeDaemon(service).start(warm=False)
    yield d
    d.drain(10)


@pytest.fixture()
def wire_client(wire_daemon):
    from consensus_specs_tpu.serve import ServeClient

    with ServeClient(wire_daemon.port, max_retries=0) as c:
        yield c


def _wire_check(i: int):
    from consensus_specs_tpu.serve.protocol import to_hex

    return {"pubkeys": [to_hex(bytes([i % 251 + 1]) * 48)],
            "message": to_hex(bytes([i % 256]) * 32),
            "signature": to_hex(b"\x02" * 96)}


def test_wire_deadline_already_expired_is_504(wire_daemon, wire_client):
    from consensus_specs_tpu.serve import ServeError

    with pytest.raises(ServeError) as e:
        wire_client.call("verify", dict(_wire_check(1), deadline_ms=0))
    assert e.value.status == 504
    assert e.value.code == protocol.DEADLINE_EXCEEDED
    rec = flightrec.requests(n=1)[0]
    assert rec["status"] == "shed_deadline"


def test_wire_deadline_applies_to_every_method(wire_client):
    from consensus_specs_tpu.serve import ServeError
    from consensus_specs_tpu.serve.protocol import to_hex

    with pytest.raises(ServeError) as e:
        wire_client.call("hash_tree_root",
                         {"fork": "phase0", "preset": "minimal",
                          "type": "Checkpoint", "ssz": to_hex(b"\x00" * 40),
                          "deadline_ms": 0})
    assert e.value.code == protocol.DEADLINE_EXCEEDED


def test_wire_field_validation(wire_client):
    from consensus_specs_tpu.serve import ServeError

    with pytest.raises(ServeError) as e:
        wire_client.call("verify", dict(_wire_check(2), priority="urgent"))
    assert e.value.status == 400
    with pytest.raises(ServeError) as e:
        wire_client.call("verify", dict(_wire_check(2), deadline_ms="soon"))
    assert e.value.status == 400
    with pytest.raises(ServeError) as e:
        wire_client.call("verify", dict(_wire_check(2), deadline_ms=-5))
    assert e.value.status == 400
    # a valid budget + class pass validation and answer normally
    assert wire_client.call("verify", dict(
        _wire_check(2), deadline_ms=30_000,
        priority="critical"))["valid"] is False


def test_debug_overload_and_prometheus_shed_counters(wire_daemon, wire_client):
    """/debug/overload exposes the admission state; serve.shed.*
    counters land in prometheus_text() (the satellite's always-on
    visibility of shedding)."""
    from consensus_specs_tpu.serve import ServeError

    with pytest.raises(ServeError):
        wire_client.call("verify", dict(_wire_check(3), deadline_ms=0))
    snap = wire_client._roundtrip("GET", "/debug/overload")
    assert snap["mode"] in ("adaptive", "fixed")
    assert snap["hard_limit"] == wire_daemon.service.batcher.max_queue
    assert snap["shed"]["admission_deadline"] >= 1
    assert "estimator" in snap and "brownout" in snap
    text = wire_client.metrics()
    assert "serve_shed_admission_deadline" in text
    assert "serve_shed_total" in text


def test_slowest_excludes_shed_requests(wire_daemon, wire_client):
    from consensus_specs_tpu.serve import ServeError

    flightrec.RECORDER.clear()
    assert wire_client.call("verify", _wire_check(7))["valid"] is False
    with pytest.raises(ServeError):
        wire_client.call("verify", dict(_wire_check(8), deadline_ms=0))
    statuses = {r["status"] for r in flightrec.requests()}
    assert "shed_deadline" in statuses and "ok" in statuses
    slowest = wire_client._roundtrip("GET", "/debug/slowest")["requests"]
    assert slowest, "served requests must still rank"
    assert all(not r["status"].startswith("shed") for r in slowest)


def test_shed_is_excluded_from_slo_availability(wire_daemon, wire_client):
    """Sheds answer 429/504 — load management, not availability burn:
    the SLO denominator (serve.responses + serve.errors.internal) must
    not move when a request is shed."""
    from consensus_specs_tpu.obs import slo
    from consensus_specs_tpu.serve import ServeError

    before = slo.observed_from_snapshot()
    with pytest.raises(ServeError):
        wire_client.call("verify", dict(_wire_check(9), deadline_ms=0))
    after = slo.observed_from_snapshot()
    assert after["requests"] == before["requests"]
    assert after["errors_5xx"] == before["errors_5xx"]
