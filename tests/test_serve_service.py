"""The serving plane's acceptance corpus: daemon answers must be
bit-identical to the direct (non-served) spec path for verify /
hash_tree_root / process_block across >=2 forks — including while a
chaos-injected backend fault degrades a batch to the host oracle
(tests/test_serve_chaos.py drills the fault half; this file proves the
clean half and the error surface)."""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.serve import (
    ServeClient,
    ServeDaemon,
    ServeError,
    SpecService,
    VerifyBatcher,
)
from consensus_specs_tpu.serve.protocol import to_hex

FORKS = ("phase0", "altair")


@pytest.fixture(scope="module")
def daemon():
    service = SpecService(forks=FORKS, presets=("minimal",),
                          batcher=VerifyBatcher(linger_ms=2))
    d = ServeDaemon(service).start(warm=False)
    yield d
    d.drain(10)


@pytest.fixture(scope="module")
def client(daemon):
    with ServeClient(daemon.port) as c:
        yield c


@pytest.fixture(scope="module")
def checks():
    from consensus_specs_tpu.crypto.bls import ciphersuite as oracle
    from consensus_specs_tpu.crypto.bls.fields import R

    sks = [31, 32]
    pks = [oracle.SkToPk(sk) for sk in sks]
    msg = b"\x2a" * 32
    sig = oracle.Sign(sum(sks) % R, msg)
    return pks, msg, sig


@pytest.fixture(scope="module")
def block_corpus(daemon):
    """Per fork: (pre_state, block) with a real randao reveal — the
    direct path and the served path both run full process_block."""
    from consensus_specs_tpu.test_framework.block import (
        apply_randao_reveal,
        build_empty_block_for_next_slot,
    )
    from consensus_specs_tpu.test_framework.context import (
        _prepare_state,
        default_activation_threshold,
        default_balances,
    )
    from consensus_specs_tpu.test_framework.state import next_slot, transition_to

    corpus = {}
    for fork in FORKS:
        spec = daemon.service._matrix[(fork, "minimal")]
        bls.bls_active = False
        state = _prepare_state(default_balances,
                               default_activation_threshold, spec).copy()
        next_slot(spec, state)
        block = build_empty_block_for_next_slot(spec, state)
        transition_to(spec, state, block.slot)
        bls.bls_active = True
        apply_randao_reveal(spec, state, block)
        corpus[fork] = (spec, state, block)
    return corpus


def test_verify_matches_direct(client, checks):
    pks, msg, sig = checks
    assert client.verify(pubkeys=pks, message=msg, signature=sig) \
        == bls.FastAggregateVerify(pks, msg, sig) is True
    assert client.verify(pubkey=pks[0], message=msg, signature=sig) \
        == bls.Verify(pks[0], msg, sig) is False
    tampered = b"\x2b" * 32
    assert client.verify(pubkeys=pks, message=tampered, signature=sig) \
        == bls.FastAggregateVerify(pks, tampered, sig) is False


def test_verify_batch_matches_direct(client, checks):
    pks, msg, sig = checks
    wire = [
        {"pubkeys": [to_hex(p) for p in pks], "message": to_hex(msg),
         "signature": to_hex(sig)},
        {"pubkeys": [to_hex(pks[0])], "message": to_hex(msg),
         "signature": to_hex(sig)},
        {"pubkeys": [to_hex(p) for p in pks],
         "messages": [to_hex(msg)] * 2, "signature": to_hex(sig)},
    ]
    direct = [
        bls.FastAggregateVerify(pks, msg, sig),
        bls.FastAggregateVerify([pks[0]], msg, sig),
        bls.AggregateVerify(pks, [msg, msg], sig),
    ]
    assert client.verify_batch(wire) == direct


@pytest.mark.parametrize("fork", FORKS)
def test_hash_tree_root_matches_direct(client, daemon, fork):
    spec = daemon.service._matrix[(fork, "minimal")]
    for type_name, obj in (
        ("Checkpoint", spec.Checkpoint(epoch=9, root=b"\x09" * 32)),
        ("Attestation", spec.Attestation()),
        ("BeaconBlockHeader", spec.BeaconBlockHeader(slot=3)),
    ):
        served = client.hash_tree_root(fork, "minimal", type_name,
                                       obj.encode_bytes())
        assert served == bytes(obj.hash_tree_root())


def test_hash_tree_root_batch(client, daemon):
    spec = daemon.service._matrix[("phase0", "minimal")]
    cp = spec.Checkpoint(epoch=1, root=b"\x01" * 32)
    out = client.call("hash_tree_root_batch", {
        "fork": "phase0", "preset": "minimal",
        "items": [{"type": "Checkpoint", "ssz": to_hex(cp.encode_bytes())},
                  {"type": "Fork", "ssz": to_hex(spec.Fork().encode_bytes())}],
    })
    assert out["roots"] == [to_hex(cp.hash_tree_root()),
                            to_hex(spec.Fork().hash_tree_root())]


@pytest.mark.parametrize("fork", FORKS)
def test_process_block_bit_identical(client, block_corpus, fork):
    spec, state, block = block_corpus[fork]
    direct = state.copy()
    spec.process_block(direct, block)
    served = client.process_block(fork, "minimal", state.encode_bytes(),
                                  block.encode_bytes())
    assert served["post"] == direct.encode_bytes()
    assert served["root"] == bytes(direct.hash_tree_root())


def test_process_block_invalid_block_is_400(client, block_corpus):
    spec, state, block = block_corpus["phase0"]
    wrong_slot = block.copy()
    wrong_slot.slot = block.slot + 1
    with pytest.raises(ServeError) as e:
        client.process_block("phase0", "minimal", state.encode_bytes(),
                             wrong_slot.encode_bytes())
    assert e.value.status == 400 and e.value.code == "bad_request"


def test_error_surface(client):
    with pytest.raises(ServeError) as e:
        client.call("hash_tree_root", {"fork": "phase0", "preset": "minimal",
                                       "type": "no_such_type", "ssz": "0x00"})
    assert e.value.status == 400
    with pytest.raises(ServeError) as e:
        client.call("hash_tree_root", {"fork": "phase0", "preset": "minimal",
                                       "type": "_cache", "ssz": "0x00"})
    assert e.value.status == 400  # private names never resolve
    with pytest.raises(ServeError) as e:
        client.call("hash_tree_root", {"fork": "bellatrix", "preset": "minimal",
                                       "type": "Checkpoint", "ssz": "0x00"})
    assert e.value.status == 400 and "matrix" in e.value.message
    with pytest.raises(ServeError) as e:
        client.call("verify", {"v": 99, "pubkey": "0x00", "message": "0x00",
                               "signature": "0x00"})
    assert e.value.status == 400 and "version" in e.value.message


def test_health_and_metrics_surface(client, daemon):
    health = client.health()
    assert health["status"] == "ready"
    assert health["wire_version"] == 1
    assert set(health["matrix"]) == {f"{f}/minimal" for f in FORKS}
    assert health["queue"]["capacity"] == daemon.service.batcher.max_queue
    text = client.metrics()
    assert "# TYPE serve_accepted counter" in text
    assert "serve_request_ms" in text
    assert client.ready() is True
