"""BLS12-381 tests: field/curve laws, pairing bilinearity, hash-to-curve
consistency, and the sign/verify/aggregate API edge cases the reference's
bls generator covers (tests/generators/bls/main.py:40-60)."""
import random

import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.crypto.bls import ciphersuite as cs
from consensus_specs_tpu.crypto.bls import hash_to_curve as h2c
from consensus_specs_tpu.crypto.bls.curve import (
    B2,
    g1_from_bytes,
    g1_generator,
    g1_to_bytes,
    g2_from_bytes,
    g2_generator,
    g2_to_bytes,
)
from consensus_specs_tpu.crypto.bls.fields import Fq2, P, R
from consensus_specs_tpu.crypto.bls.pairing import pairing

pytestmark = pytest.mark.bls


def test_generators_valid():
    g1, g2 = g1_generator(), g2_generator()
    assert g1.on_curve() and g2.on_curve()
    assert g1.in_subgroup() and g2.in_subgroup()
    assert g1.mul(R).is_infinity and g2.mul(R).is_infinity


def test_point_serialization_roundtrip():
    rng = random.Random(5)
    for _ in range(4):
        k = rng.randrange(1, R)
        p1 = g1_generator().mul(k)
        assert g1_from_bytes(g1_to_bytes(p1)) == p1
        p2 = g2_generator().mul(k)
        assert g2_from_bytes(g2_to_bytes(p2)) == p2
    # known anchor: pubkey for sk=1 is the compressed G1 generator
    assert cs.SkToPk(1).hex().startswith("97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58")


def test_infinity_serialization():
    inf1 = g1_generator().infinity()
    assert g1_to_bytes(inf1)[0] == 0xC0
    assert g1_from_bytes(g1_to_bytes(inf1)).is_infinity
    assert cs.G2_POINT_AT_INFINITY == g2_to_bytes(g2_generator().infinity())
    assert g2_from_bytes(cs.G2_POINT_AT_INFINITY).is_infinity


def test_pairing_bilinearity():
    e = pairing(g1_generator(), g2_generator())
    assert not e.is_one()
    assert e.pow(R).is_one()
    assert pairing(g1_generator().mul(3), g2_generator().mul(4)) == e.pow(12)


def test_sswu_and_iso_on_curve():
    rng = random.Random(42)
    for _ in range(3):
        u = Fq2(rng.randrange(P), rng.randrange(P))
        x, y = h2c.map_to_curve_simple_swu(u)
        assert y.square() == x * x.square() + h2c._A * x + h2c._B
        xo, yo = h2c.iso_map_g2(x, y)
        assert yo.square() == xo * xo.square() + B2


def test_hash_to_g2_subgroup_and_determinism():
    p1 = h2c.hash_to_g2(b"test message")
    p2 = h2c.hash_to_g2(b"test message")
    p3 = h2c.hash_to_g2(b"other message")
    assert p1 == p2 and p1 != p3
    assert p1.on_curve() and p1.in_subgroup()


def test_expand_message_xmd_shapes():
    out = h2c.expand_message_xmd(b"msg", b"DST", 96)
    assert len(out) == 96
    assert h2c.expand_message_xmd(b"msg", b"DST", 96) == out
    assert h2c.expand_message_xmd(b"msg2", b"DST", 96) != out


def test_sign_verify():
    sk, msg = 12345, b"hello consensus"
    pk = cs.SkToPk(sk)
    sig = cs.Sign(sk, msg)
    assert cs.Verify(pk, msg, sig)
    assert not cs.Verify(pk, b"wrong message", sig)
    assert not cs.Verify(cs.SkToPk(54321), msg, sig)
    # tampered signature
    bad = bytearray(sig)
    bad[-1] ^= 1
    assert not cs.Verify(pk, msg, bytes(bad))


def test_aggregate_same_message():
    msg = b"attestation data root"
    sks = [101, 202, 303]
    pks = [cs.SkToPk(sk) for sk in sks]
    sigs = [cs.Sign(sk, msg) for sk in sks]
    agg = cs.Aggregate(sigs)
    assert cs.FastAggregateVerify(pks, msg, agg)
    assert not cs.FastAggregateVerify(pks[:2], msg, agg)
    assert not cs.FastAggregateVerify(pks, b"other", agg)
    # aggregated pubkey verifies as a plain key
    assert cs.Verify(cs.AggregatePKs(pks), msg, agg)


def test_aggregate_distinct_messages():
    pairs = [(7, b"m1"), (8, b"m2"), (9, b"m3")]
    pks = [cs.SkToPk(sk) for sk, _ in pairs]
    msgs = [m for _, m in pairs]
    agg = cs.Aggregate([cs.Sign(sk, m) for sk, m in pairs])
    assert cs.AggregateVerify(pks, msgs, agg)
    assert not cs.AggregateVerify(pks, [b"m1", b"m2", b"mX"], agg)
    assert not cs.AggregateVerify(list(reversed(pks)), msgs, agg)


def test_edge_cases():
    # empty-input rules (bls generator edge vectors, generators/bls/main.py:56-60)
    with pytest.raises(Exception):
        cs.Aggregate([])
    assert not cs.FastAggregateVerify([], b"msg", cs.G2_POINT_AT_INFINITY)
    assert not cs.AggregateVerify([], [], cs.G2_POINT_AT_INFINITY)
    # infinity pubkey fails KeyValidate and Verify
    inf_pk = g1_to_bytes(g1_generator().infinity())
    assert not cs.KeyValidate(inf_pk)
    assert not cs.Verify(inf_pk, b"msg", cs.G2_POINT_AT_INFINITY)
    assert cs.KeyValidate(cs.SkToPk(1))
    with pytest.raises(ValueError):
        cs.Sign(0, b"msg")
    with pytest.raises(ValueError):
        cs.Sign(R, b"msg")


def test_facade_switch():
    sk, msg = 42, b"facade"
    pk, sig = bls.SkToPk(sk), bls.Sign(sk, msg)
    assert bls.Verify(pk, msg, sig)
    assert not bls.Verify(pk, msg, b"\x00" * 96)  # exception-swallowing path
    bls.bls_active = False
    try:
        assert bls.Verify(pk, b"anything", b"junk")  # skipped -> True
    finally:
        bls.bls_active = True


def test_clear_cofactor_psi_equals_h_eff():
    """The psi-decomposition fast path must EXACTLY equal the RFC 9380
    [h_eff]Q ladder — same point, not just same subgroup."""
    from consensus_specs_tpu.crypto.bls import hash_to_curve as h2c
    from consensus_specs_tpu.crypto.bls.curve import g2_generator

    for msg in (b"", b"psi-check", b"\xff" * 48):
        u0, u1 = h2c.hash_to_field_fq2(msg, 2, h2c.DST_G2_POP)
        q = h2c.map_to_curve_g2(u0).add(h2c.map_to_curve_g2(u1))
        assert h2c.clear_cofactor(q).affine() == q.mul(h2c.H_EFF).affine()
    g = g2_generator()
    assert h2c.clear_cofactor(g).affine() == g.mul(h2c.H_EFF).affine()
