"""Pipeline-overlap hashing (ops/sha256.hash_many_pipelined) and the
profiling hooks (utils/profiling) — the SURVEY §2.6 pipeline row and §5
tracing row."""
import hashlib

import numpy as np

from consensus_specs_tpu.ops import sha256 as dev
from consensus_specs_tpu.utils import profiling


def test_hash_many_pipelined_matches_host():
    rng = np.random.default_rng(5)
    batches = [rng.integers(0, 256, size=(64 * n,), dtype=np.uint8).tobytes() for n in (1, 3, 8, 5)]
    got = dev.hash_many_pipelined(batches)
    for data, out in zip(batches, got):
        want = b"".join(
            hashlib.sha256(data[i : i + 64]).digest() for i in range(0, len(data), 64)
        )
        assert out == want


def test_profiling_sections_accumulate():
    profiling.report(reset=True)
    with profiling.section("unit"):
        pass
    with profiling.section("unit"):
        pass

    @profiling.annotate("deco")
    def f():
        return 7

    assert f() == 7
    rows = profiling.report(reset=True)
    assert rows["unit"]["calls"] == 2
    assert rows["deco"]["calls"] == 1


def test_trace_noop_without_env(monkeypatch):
    monkeypatch.delenv("CONSENSUS_SPECS_TPU_TRACE_DIR", raising=False)
    with profiling.trace("x"):
        pass  # must not require jax profiler infrastructure
