"""Chaos replay: a generated corpus with tampered fixtures — truncated
``.ssz_snappy``, malformed ``data.yaml``/``mapping.yaml``/``slots.yaml``,
missing parts — must degrade gracefully through tools/replay_vectors:
every tampered case is flagged with the ``corruption`` taxonomy class,
untampered cases keep replaying clean, and the walk never aborts on the
first bad file."""
from __future__ import annotations

import pathlib
import shutil
import tempfile

import pytest
import yaml

from consensus_specs_tpu import resilience as r
from consensus_specs_tpu.generators.gen_from_tests import generate_from_tests
from consensus_specs_tpu.generators.gen_runner import run_generator
from consensus_specs_tpu.generators.gen_typing import TestProvider
from consensus_specs_tpu.utils import snappy
from tools.replay_vectors import replay_tree, summarize_failures


@pytest.fixture(scope="module")
def corpus():
    """A small sanity/slots corpus (pre + slots.yaml + post per case) —
    the cheapest format family carrying both ssz and yaml parts."""
    import tests.spec.test_sanity_slots as slots_src

    with tempfile.TemporaryDirectory() as out:
        def make():
            yield from generate_from_tests(
                runner_name="sanity",
                handler_name="slots",
                src=slots_src,
                fork_name="phase0",
                preset_name="minimal",
                bls_active=False,
                phase=None,
            )

        run_generator(
            "sanity",
            [TestProvider(prepare=lambda: None, make_cases=make)],
            args=["-o", out],
        )
        yield pathlib.Path(out)


def _tampered_copy(corpus: pathlib.Path, dest: str) -> pathlib.Path:
    work = pathlib.Path(dest)
    shutil.copytree(corpus, work, dirs_exist_ok=True)
    return work


def _case_dirs(root: pathlib.Path):
    return sorted(p.parent for p in root.rglob("slots.yaml"))


def test_clean_corpus_replays_ok(corpus):
    ok, failed, unsupported, incomplete = replay_tree(corpus)
    assert failed == [] and ok >= 3
    assert unsupported == 0 and incomplete == 0


def test_every_tamper_class_is_flagged_as_corruption(corpus, tmp_path):
    work = _tampered_copy(corpus, tmp_path / "work")
    cases = _case_dirs(work)
    assert len(cases) >= 3, "need at least 3 cases to tamper independently"

    tampered = {}

    # (1) truncated ssz part: survives nothing — the snappy CRC catches it
    post = cases[0] / "post.ssz_snappy"
    post.write_bytes(post.read_bytes()[: max(1, post.stat().st_size // 2)])
    tampered[str(cases[0].relative_to(work))] = "truncated ssz_snappy"

    # (2) malformed yaml data part
    (cases[1] / "slots.yaml").write_text("{unclosed: [")
    tampered[str(cases[1].relative_to(work))] = "malformed yaml"

    # (3) missing part: pre state deleted out from under the case
    (cases[2] / "pre.ssz_snappy").unlink()
    tampered[str(cases[2].relative_to(work))] = "missing part"

    # (4) handcrafted bls case with malformed data.yaml (the yaml-only
    # format family the replayer walks via *.yaml)
    bls_case = work / "general/phase0/bls/verify/small/corrupt_case"
    bls_case.mkdir(parents=True)
    (bls_case / "data.yaml").write_text("input: {pubkey: [unterminated")
    tampered[str(bls_case.relative_to(work))] = "malformed bls data.yaml"

    # (5) handcrafted shuffling case with malformed mapping.yaml
    shuf_case = work / "minimal/phase0/shuffling/core/shuffle/corrupt_case"
    shuf_case.mkdir(parents=True)
    (shuf_case / "mapping.yaml").write_text("seed: '0x' mapping: [")
    tampered[str(shuf_case.relative_to(work))] = "malformed mapping.yaml"

    ok, failed, unsupported, incomplete = replay_tree(work)

    # the walk completed and flagged EVERY tampered case — exactly those
    failed_paths = {rel for rel, _ in failed}
    assert failed_paths == set(tampered), (
        f"flagged {failed_paths} vs tampered {set(tampered)}")
    # all classified as corruption, visible in the structured summary
    assert summarize_failures(failed) == {"corruption": len(tampered)}
    for f in failed:
        assert f.taxonomy == "corruption"
        assert f[1].startswith("[corruption] ")
    # untampered cases still replayed clean (graceful degradation)
    assert ok == len(_case_dirs(work)) - 3


def test_divergence_classified_separately_from_corruption(corpus, tmp_path):
    """A corrupted POST STATE that still decodes is a divergence (the
    replay ran, the bytes disagree) — not corpus corruption."""
    work = _tampered_copy(corpus, tmp_path / "work")
    case = _case_dirs(work)[0]
    post = case / "post.ssz_snappy"
    raw = bytearray(snappy.decompress(post.read_bytes()))
    raw[0] ^= 0xFF
    post.write_bytes(snappy.compress(bytes(raw)))

    ok, failed, _, _ = replay_tree(work)
    assert len(failed) == 1
    assert failed[0].taxonomy == "divergence"
    assert "post mismatch" in failed[0][1]


def test_injected_replay_fault_is_classified(corpus, monkeypatch):
    """The env knob drives injection INTO the replayer loop itself."""
    monkeypatch.setenv(r.ENV_KNOB, "replay.case=deterministic:1")
    r.refresh()
    try:
        ok, failed, _, _ = replay_tree(corpus)
        assert len(failed) == 1
        assert failed[0].taxonomy == "deterministic"
        assert ok == len(_case_dirs(corpus)) - 1
    finally:
        monkeypatch.delenv(r.ENV_KNOB)
        r.refresh()
