"""Incremental Merkleization correctness: cached roots must equal
from-scratch roots after every mutation pattern the spec exercises.

The oracle is decode(encode(x)).hash_tree_root() — a fresh value with no
caches. Mirrors the guarantee remerkleable provides the reference
(eth2spec/utils/ssz/ssz_impl.py:11-13) for our dirty-tracking backing
(ssz/backing.py).
"""
import random

import pytest

from consensus_specs_tpu.ssz.types import (
    Bitlist,
    Bitvector,
    ByteList,
    Bytes32,
    Bytes48,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint8,
    uint64,
    uint256,
)


def fresh_root(obj) -> bytes:
    """From-scratch root: round-trip through serialization (no caches)."""
    return type(obj).decode_bytes(obj.encode_bytes()).hash_tree_root()


def check(obj) -> None:
    assert obj.hash_tree_root() == fresh_root(obj)


class Inner(Container):
    a: uint64
    b: Bytes32


class Flat(Container):  # matches the Validator shape: all-immutable fields
    pubkey: Bytes48
    credentials: Bytes32
    balance: uint64
    slashed: boolean


class Outer(Container):
    slot: uint64
    inner: Inner
    nums: List[uint64, 1024]
    flats: List[Flat, 2**40]
    bits: Bitlist[64]
    vec: Vector[uint64, 8]


def make_outer(n_flats=5) -> Outer:
    return Outer(
        slot=3,
        inner=Inner(a=7, b=Bytes32(b"\x11" * 32)),
        nums=list(range(10)),
        flats=[Flat(pubkey=Bytes48(bytes([i]) * 48), balance=i) for i in range(n_flats)],
        bits=[True, False, True],
        vec=list(range(8)),
    )


class TestScalarMutations:
    def test_container_field(self):
        o = make_outer()
        check(o)
        o.slot = 99
        check(o)

    def test_nested_container_field(self):
        o = make_outer()
        check(o)
        o.inner.a = 1234  # mutation through a held reference
        check(o)

    def test_nested_via_reference(self):
        o = make_outer()
        check(o)
        inner = o.inner
        inner.b = Bytes32(b"\x22" * 32)
        check(o)

    def test_basic_list_setitem(self):
        o = make_outer()
        check(o)
        o.nums[3] = 777
        check(o)

    def test_composite_list_item_mutation(self):
        o = make_outer()
        check(o)
        o.flats[2].balance = 10**9
        check(o)

    def test_bitlist_setitem(self):
        o = make_outer()
        check(o)
        o.bits[1] = True
        check(o)

    def test_vector_setitem(self):
        o = make_outer()
        check(o)
        o.vec[-1] = 4242
        check(o)


class TestLengthMutations:
    def test_append_basic(self):
        o = make_outer()
        check(o)
        o.nums.append(123)
        check(o)

    def test_append_composite(self):
        o = make_outer()
        check(o)
        o.flats.append(Flat(balance=55))
        check(o)

    def test_pop_basic(self):
        o = make_outer()
        check(o)
        o.nums.pop()
        check(o)

    def test_pop_composite(self):
        o = make_outer()
        check(o)
        o.flats.pop()
        check(o)

    def test_pop_across_chunk_boundary(self):
        # 5 uint64s = 2 chunks; popping to 4 keeps one full chunk
        nums = List[uint64, 64](1, 2, 3, 4, 5)
        check(nums)
        nums.pop()
        check(nums)
        nums.pop()
        check(nums)

    def test_drain_and_refill(self):
        nums = List[uint64, 64](1, 2, 3)
        check(nums)
        while len(nums):
            nums.pop()
            check(nums)
        for i in range(7):
            nums.append(i * 11)
            check(nums)

    def test_mutate_without_prior_root(self):
        # first root AFTER mutations — full-build path
        o = make_outer()
        o.slot = 5
        o.nums.append(9)
        check(o)


class TestSharing:
    def test_aliased_child_invalidates_both_parents(self):
        shared = Inner(a=1)
        o1 = Outer(inner=shared)
        o2 = Outer(inner=shared, slot=9)
        check(o1)
        check(o2)
        shared.a = 42
        check(o1)
        check(o2)

    def test_replaced_child_stale_link_harmless(self):
        o = make_outer()
        old = o.inner
        check(o)
        o.inner = Inner(a=5)
        check(o)
        old.a = 77  # stale parent link: spurious invalidation only
        check(o)

    def test_copy_is_independent(self):
        o = make_outer()
        check(o)
        c = o.copy()
        assert c.hash_tree_root() == o.hash_tree_root()
        c.inner.a = 999
        c.flats[0].balance = 888
        check(c)
        check(o)
        assert c.hash_tree_root() != o.hash_tree_root()
        # and the original still updates correctly
        o.nums[0] = 4
        check(o)

    def test_copy_preserves_incremental_updates(self):
        o = make_outer(n_flats=100)
        check(o)
        c = o.copy()
        c.flats[50].balance = 123456
        check(c)


class TestBatchedLeafPath:
    def test_batched_matches_per_item(self):
        # >=64 flat containers takes _batched_container_roots
        flats = List[Flat, 2**40]([Flat(pubkey=Bytes48(bytes([i % 251]) * 48), balance=i) for i in range(200)])
        got = flats.hash_tree_root()
        assert got == fresh_root(flats)
        # per-item oracle
        one = Flat(pubkey=Bytes48(bytes([7]) * 48), balance=7)
        assert flats[7].hash_tree_root() == one.hash_tree_root()

    def test_batched_then_incremental(self):
        flats = List[Flat, 2**40]([Flat(balance=i) for i in range(128)])
        check(flats)
        flats[65].balance = 1
        flats[0].slashed = True
        check(flats)
        flats.append(Flat(balance=999))
        check(flats)


class TestRandomizedTrace:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_mutation_trace(self, seed):
        rng = random.Random(seed)
        o = make_outer(n_flats=rng.randint(0, 80))
        for step in range(60):
            op = rng.randrange(9)
            if op == 0:
                o.slot = rng.getrandbits(32)
            elif op == 1:
                o.inner.a = rng.getrandbits(32)
            elif op == 2 and len(o.nums) < 1024:
                o.nums.append(rng.getrandbits(20))
            elif op == 3 and len(o.nums):
                o.nums[rng.randrange(len(o.nums))] = rng.getrandbits(20)
            elif op == 4 and len(o.nums):
                o.nums.pop()
            elif op == 5:
                o.flats.append(Flat(balance=rng.getrandbits(20)))
            elif op == 6 and len(o.flats):
                o.flats[rng.randrange(len(o.flats))].balance = rng.getrandbits(20)
            elif op == 7 and len(o.flats):
                o.flats.pop()
            elif op == 8:
                o.bits[rng.randrange(len(o.bits))] = rng.random() < 0.5
            if rng.random() < 0.4:  # interleave root requests with mutations
                check(o)
        check(o)


class TestUnionAndBytes:
    def test_union_value_mutation(self):
        U = Union[None, Inner]
        u = U(1, Inner(a=3))
        check(u)
        u.value.a = 9
        check(u)

    def test_bytelist_cached(self):
        bl = ByteList[256](b"hello world")
        assert bl.hash_tree_root() == bl.hash_tree_root()
        assert bl.hash_tree_root() == fresh_root(bl)

    def test_uint256_list(self):
        xs = List[uint256, 64]([2**200, 5])
        check(xs)
        xs[0] = 77
        check(xs)
        xs.append(2**255 - 1)
        check(xs)

    def test_uint8_packing(self):
        xs = List[uint8, 1000](list(range(100)))
        check(xs)
        xs[31] = 255  # last element of chunk 0
        xs[32] = 254  # first element of chunk 1
        check(xs)
