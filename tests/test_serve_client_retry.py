"""Client-side overload discipline (ISSUE 10, docs/SERVE.md "Overload
control"): the token-bucket retry budget, jittered exponential backoff,
which refusals are retryable (queue_full/draining/torn sockets — never
shed or deadline_exceeded), and end-to-end deadline propagation on the
wire. The core property under drill: retries can never multiply offered
load unboundedly — an empty budget surfaces the original error."""
import os
import random
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import obs
from consensus_specs_tpu.obs import flightrec
from consensus_specs_tpu.serve import (
    RetryBudget,
    ServeClient,
    ServeDaemon,
    ServeError,
    SpecService,
    VerifyBatcher,
)
from consensus_specs_tpu.serve import protocol


def _wire_check(i: int):
    from consensus_specs_tpu.serve.protocol import to_hex

    return {"pubkeys": [to_hex(bytes([i % 251 + 1]) * 48)],
            "message": to_hex(bytes([i % 256]) * 32),
            "signature": to_hex(b"\x02" * 96)}


def test_retry_budget_token_bucket():
    budget = RetryBudget(capacity=2.0, ratio=0.5)
    assert budget.try_spend() and budget.try_spend()
    assert not budget.try_spend()  # empty
    budget.deposit()  # +0.5
    assert not budget.try_spend()  # still < 1 token
    budget.deposit()
    assert budget.try_spend()
    for _ in range(100):
        budget.deposit()
    assert budget.tokens == pytest.approx(2.0)  # capped at capacity


def test_retryable_classification():
    retryable = ServeClient._retryable
    assert retryable(ServeError(429, protocol.QUEUE_FULL, ""))
    assert retryable(ServeError(503, protocol.DRAINING, ""))
    assert retryable(ConnectionResetError())
    # the daemon said "stop adding load" / "budget spent": NOT retryable
    assert not retryable(ServeError(429, protocol.SHED, ""))
    assert not retryable(ServeError(504, protocol.DEADLINE_EXCEEDED, ""))
    assert not retryable(ServeError(400, protocol.BAD_REQUEST, ""))
    assert not retryable(ServeError(500, protocol.INTERNAL, ""))


def test_backoff_is_jittered_exponential_and_deadline_capped():
    c = ServeClient(1, rng=random.Random(7), backoff_base_ms=100,
                    backoff_cap_ms=300)
    samples0 = [c._backoff_s(0, None) for _ in range(200)]
    samples2 = [c._backoff_s(2, None) for _ in range(200)]
    assert all(0 <= s <= 0.1 for s in samples0)
    assert all(0 <= s <= 0.3 for s in samples2)  # capped below 400ms
    assert max(samples2) > max(samples0)  # the envelope grew
    assert len({round(s, 6) for s in samples0}) > 50  # full jitter
    assert c._backoff_s(5, remaining_ms=10.0) <= 0.010  # never past deadline


@pytest.fixture(scope="module")
def stuck_daemon():
    """A daemon whose 1-slot queue never flushes (long linger): every
    submit past the first is a deterministic queue_full 429."""
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=VerifyBatcher(max_queue=1,
                                                linger_ms=60_000,
                                                cache_size=0),
                          request_timeout_s=60)
    d = ServeDaemon(service).start(warm=False)
    blocker = threading.Thread(
        target=lambda: ServeClient(d.port, timeout_s=60, max_retries=0).call(
            "verify", _wire_check(0)),
        daemon=True)
    blocker.start()
    deadline = time.monotonic() + 30
    while d.service.batcher.depth() < 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    yield d
    d.drain(10)


def test_retries_happen_with_backoff_then_surface(stuck_daemon):
    snap0 = obs.snapshot()["counters"].get("serve.client.retries", 0)
    c = ServeClient(stuck_daemon.port, max_retries=2,
                    retry_budget=RetryBudget(capacity=10, ratio=0.1),
                    backoff_base_ms=1, rng=random.Random(3))
    with pytest.raises(ServeError) as e:
        c.call("verify", _wire_check(1))
    assert e.value.code == protocol.QUEUE_FULL  # surfaced after retries
    assert c.retries == 2
    assert obs.snapshot()["counters"]["serve.client.retries"] == snap0 + 2
    c.close()


def test_exhausted_budget_blocks_retries_and_is_recorded(stuck_daemon):
    flightrec.RECORDER.clear()
    c = ServeClient(stuck_daemon.port, max_retries=5,
                    retry_budget=RetryBudget(capacity=1, ratio=0.0),
                    backoff_base_ms=1, rng=random.Random(5))
    with pytest.raises(ServeError):
        c.call("verify", _wire_check(2))  # spends the single token
    assert c.retries == 1
    with pytest.raises(ServeError) as e:
        c.call("verify", _wire_check(3))  # budget empty: NO retry
    assert e.value.code == protocol.QUEUE_FULL
    assert c.retries == 1  # unchanged — the retry never happened
    assert obs.snapshot()["counters"]["serve.client.retry_budget_exhausted"] >= 1
    recorded = [r for r in flightrec.requests()
                if r["status"] == "retry_budget_exhausted"]
    assert recorded, "budget exhaustion must land in the flight recorder"
    c.close()


def test_shared_budget_bounds_fleet_amplification(stuck_daemon):
    """One budget across N client threads: total retries across the
    fleet are bounded by the bucket, not N * max_retries."""
    shared = RetryBudget(capacity=3, ratio=0.0)
    clients = [ServeClient(stuck_daemon.port, max_retries=4,
                           retry_budget=shared, backoff_base_ms=1,
                           rng=random.Random(i)) for i in range(6)]
    errors = []

    def worker(c, i):
        try:
            c.call("verify", _wire_check(10 + i))
        except ServeError as e:
            errors.append(e)
        finally:
            c.close()

    threads = [threading.Thread(target=worker, args=(c, i))
               for i, c in enumerate(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert len(errors) == 6  # every caller surfaced the refusal
    assert sum(c.retries for c in clients) == 3  # exactly the bucket


def test_client_deadline_expires_locally_without_a_round_trip():
    c = ServeClient(1, deadline_ms=0.0)  # port never dialed
    with pytest.raises(ServeError) as e:
        c.call("verify", _wire_check(4))
    assert e.value.code == protocol.DEADLINE_EXCEEDED
    assert e.value.status == 504


def test_deadline_propagates_on_the_wire():
    """A client-level budget rides the wire as deadline_ms: a daemon
    whose estimator has real slow-drain evidence rejects the tight
    budget at admission with 504 deadline_exceeded — which the client
    must surface, not retry. The daemon can only have done that if the
    client actually injected the field."""
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=VerifyBatcher(max_batch=1, linger_ms=1,
                                                cache_size=0,
                                                flush_delay_ms=250.0),
                          request_timeout_s=60)
    d = ServeDaemon(service).start(warm=False)
    try:
        with ServeClient(d.port, max_retries=0, timeout_s=60) as warm:
            for i in range(2):  # teach the estimator the ~4 rows/s drain
                warm.call("verify", _wire_check(20 + i))
        holders = [threading.Thread(
            target=lambda i=i: ServeClient(d.port, timeout_s=60,
                                           max_retries=0).call(
                "verify", _wire_check(30 + i)), daemon=True)
            for i in range(2)]
        for t in holders:
            t.start()
        deadline = time.monotonic() + 30
        while service.batcher.depth() < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        c = ServeClient(d.port, max_retries=3, backoff_base_ms=1,
                        deadline_ms=100.0)
        t0 = time.monotonic()
        with pytest.raises(ServeError) as e:
            c.call("verify", _wire_check(5))
        assert e.value.code == protocol.DEADLINE_EXCEEDED
        assert e.value.status == 504
        assert c.retries == 0  # deadline_exceeded is never retried
        assert time.monotonic() - t0 < 10
        c.close()
        for t in holders:
            t.join(30)
    finally:
        d.drain(15)


def test_priority_defaults_ride_every_call(stuck_daemon):
    """A client-wide priority=sheddable is injected into the params —
    proven by the 400 a bogus class draws vs the clean validation a
    real one passes (the daemon parses what the client sent)."""
    c = ServeClient(stuck_daemon.port, max_retries=0, priority="bogus")
    with pytest.raises(ServeError) as e:
        c.call("verify", _wire_check(6))
    assert e.value.status == 400 and "priority" in e.value.message
    c.close()
