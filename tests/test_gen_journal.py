"""Generator crash safety: a run killed with SIGKILL mid-generation must
resume from the journal on rerun and produce a byte-identical vector
tree; corrupted committed output (truncated parts, tampered yaml) must
be detected at resume and regenerated, never silently shipped; injected
transient faults inside case execution retry to success."""
from __future__ import annotations

import os
import pathlib
import signal
import subprocess
import sys

import pytest

from consensus_specs_tpu import resilience as r
from consensus_specs_tpu.resilience import journal as journal_mod

REPO = pathlib.Path(__file__).resolve().parent.parent
DRIVER = REPO / "tests" / "_gen_journal_driver.py"


def _run_driver(out_dir: pathlib.Path, chaos: str = "") -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("CONSENSUS_SPECS_TPU_CHAOS_STATE", None)
    if chaos:
        env[r.ENV_KNOB] = chaos
    else:
        env.pop(r.ENV_KNOB, None)
    return subprocess.run(
        [sys.executable, str(DRIVER), str(out_dir)],
        env=env, cwd=str(REPO), capture_output=True, text=True, timeout=300,
    )


def _tree(root: pathlib.Path) -> dict:
    """{relative path: bytes} over the corpus, minus journal/log files."""
    skip = {journal_mod.JOURNAL_NAME, "testgen_error_log.txt"}
    return {
        str(p.relative_to(root)): p.read_bytes()
        for p in sorted(root.rglob("*"))
        if p.is_file() and p.name not in skip
    }


@pytest.fixture(scope="module")
def clean_tree(tmp_path_factory):
    out = tmp_path_factory.mktemp("gen_clean")
    proc = _run_driver(out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    tree = _tree(out)
    assert len(tree) >= 9, "expected at least 3 cases x 3 parts"
    return tree


def test_kill9_then_rerun_resumes_byte_identical(clean_tree, tmp_path):
    out = tmp_path / "vectors"
    # the chaos 'kill' kind delivers SIGKILL to the generator process at
    # the start of the 3rd case — a genuine kill -9 mid-generation
    proc = _run_driver(out, chaos="gen.case=kill:1:2")
    assert proc.returncode == -signal.SIGKILL, (
        f"rc={proc.returncode}; stdout tail: {proc.stdout[-500:]}")
    partial = _tree(out)
    assert 0 < len(partial) < len(clean_tree), "the kill must land mid-run"

    # rerun without injection: journal-verified resume completes the tree
    proc = _run_driver(out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "generating: " in proc.stdout  # some cases actually regenerated
    assert _tree(out) == clean_tree  # byte-identical to the uninterrupted run

    # committed-before-kill cases were admitted from the journal, not
    # regenerated: the resume run skipped at least the first two
    assert proc.stdout.count("generating: ") < len(clean_tree) // 3 + 1


def test_corrupted_output_detected_and_regenerated(clean_tree, tmp_path):
    out = tmp_path / "vectors"
    assert _run_driver(out).returncode == 0

    # tamper two committed cases behind the journal's back
    files = sorted(out.rglob("*.ssz_snappy"))
    truncated = files[0]
    truncated.write_bytes(truncated.read_bytes()[:10])
    yamls = sorted(out.rglob("slots.yaml"))
    tampered_yaml = yamls[-1]
    tampered_yaml.write_text("]]malformed[[")

    # a plain rerun (no --force) must catch both, regenerate, and land
    # byte-identical to the clean tree
    proc = _run_driver(out)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.count("failed resume verification") == 2
    assert _tree(out) == clean_tree


def test_untampered_resume_skips_everything(clean_tree, tmp_path):
    out = tmp_path / "vectors"
    assert _run_driver(out).returncode == 0
    proc = _run_driver(out)
    assert proc.returncode == 0
    assert "generating: " not in proc.stdout  # full skip, no regeneration
    assert _tree(out) == clean_tree


def test_transient_case_fault_retried_to_success(clean_tree, tmp_path):
    """Injected transient inside case execution: the supervisor retries
    and the run completes with zero failed cases and identical bytes."""
    out = tmp_path / "vectors"
    proc = _run_driver(out, chaos="gen.case=transient:2")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "0 failed" in proc.stdout.replace(", ", " ").replace("failed,", "failed") or \
        " 0 failed" in proc.stdout
    assert _tree(out) == clean_tree


def test_deterministic_case_fault_counts_failed_and_leaves_incomplete(tmp_path):
    out = tmp_path / "vectors"
    proc = _run_driver(out, chaos="gen.case=deterministic:1")
    assert proc.returncode == 1  # run_generator exits 1 on failed cases
    assert "DeterministicFault" in (out / "testgen_error_log.txt").read_text()
    incompletes = list(out.rglob("INCOMPLETE"))
    assert len(incompletes) == 1
    # and a rerun heals the failed case to a complete tree
    proc = _run_driver(out)
    assert proc.returncode == 0
    assert not list(out.rglob("INCOMPLETE"))
