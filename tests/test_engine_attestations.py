"""Differential tests for the engine's batched ``process_attestation``
path (engine/attestations.process_attestations_batch + the
use_batched_attestations() install): random attestation batches across
all four production forks must leave a bit-identical state vs the
interpreted per-attestation oracle loop — including the partial state an
INVALID attestation leaves behind when it is rejected mid-batch.
Host-only and fast (tier-1 CI).
"""
from __future__ import annotations

import random

import pytest

from consensus_specs_tpu import engine
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.engine.attestations import process_attestations_batch
from consensus_specs_tpu.specs import build_spec
from consensus_specs_tpu.test_framework import context as tf_context
from consensus_specs_tpu.test_framework.attestations import (
    get_valid_attestation,
    next_slots_with_attestations,
)

FORKS = engine.SUPPORTED_FORKS


@pytest.fixture(autouse=True)
def _clean_engine_and_bls():
    engine.use_interpreted_epoch()
    engine.use_direct_attestations()
    was = bls.bls_active
    bls.bls_active = False  # protocol-plane parity; signatures stubbed
    yield
    bls.bls_active = was
    engine.use_interpreted_epoch()
    engine.use_direct_attestations()


def _advanced_state(spec, slots=12):
    state = tf_context._prepare_state(
        tf_context.default_balances, tf_context.default_activation_threshold, spec)
    _, blocks, post = next_slots_with_attestations(spec, state, slots, True, True)
    return post, blocks


def _random_batch(spec, state, rng, n=8):
    """Random valid attestations over the includable slot window, mixed
    committees and participation subsets (duplicates included — the spec
    processes them; repeated flags must yield no double proposer reward)."""
    atts = []
    spe = int(spec.SLOTS_PER_EPOCH)
    lo = max(0, int(state.slot) - spe + 1)
    hi = int(state.slot) - int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
    for _ in range(n):
        slot = rng.randint(lo, hi)
        committees = int(spec.get_committee_count_per_slot(
            state, spec.compute_epoch_at_slot(spec.Slot(slot))))
        index = rng.randrange(committees)
        frac = rng.choice([0.4, 0.8, 1.0])
        try:
            att = get_valid_attestation(
                spec, state, slot=spec.Slot(slot),
                index=spec.CommitteeIndex(index),
                filter_participant_set=lambda comm: {
                    i for i in comm if rng.random() < frac},
            )
        except AssertionError:
            continue
        if any(att.aggregation_bits):
            atts.append(att)
    # duplicates: the same attestation twice exercises the already-set
    # flag path (proposer reward must NOT be granted twice)
    if atts:
        atts.append(atts[0])
    return atts


def _roots_after(spec, state, atts, use_batch):
    st = state.copy()
    if use_batch:
        process_attestations_batch(spec, st, atts)
    else:
        for a in atts:
            spec.process_attestation(st, a)
    return bytes(st.hash_tree_root()), st


@pytest.mark.parametrize("fork", FORKS)
def test_random_batches_bit_identical(fork):
    spec = build_spec(fork, "minimal")
    state, _ = _advanced_state(spec)
    for seed in (0, 1, 2):
        rng = random.Random(seed)
        atts = _random_batch(spec, state, rng)
        assert atts, "workload generator produced no attestations"
        oracle_root, _ = _roots_after(spec, state, atts, use_batch=False)
        batch_root, _ = _roots_after(spec, state, atts, use_batch=True)
        assert oracle_root == batch_root, f"{fork} seed={seed} diverged"


@pytest.mark.parametrize("fork", FORKS)
def test_real_block_attestations_bit_identical(fork):
    """The batch on real block bodies: every attestation-carrying block
    from a 12-slot chain, replayed through both paths."""
    spec = build_spec(fork, "minimal")
    state = tf_context._prepare_state(
        tf_context.default_balances, tf_context.default_activation_threshold, spec)
    _, blocks, _ = next_slots_with_attestations(spec, state, 12, True, True)
    carrier = [b for b in blocks if len(b.message.body.attestations)]
    assert carrier
    # rebuild the pre-state of the last carrier block
    st = tf_context._prepare_state(
        tf_context.default_balances, tf_context.default_activation_threshold, spec)
    target = carrier[-1]
    for b in blocks:
        if b is target:
            break
        spec.state_transition(st, b, True)
    spec.process_slots(st, target.message.slot)
    atts = list(target.message.body.attestations)
    oracle_root, _ = _roots_after(spec, st, atts, use_batch=False)
    batch_root, _ = _roots_after(spec, st, atts, use_batch=True)
    assert oracle_root == batch_root


def _tampered(spec, att, mode):
    bad = att.copy()
    if mode == "bad_index":
        bad.data.index = spec.get_committee_count_per_slot(
            spec.BeaconState(), spec.Epoch(0)) + 64
    elif mode == "bad_source":
        bad.data.source = spec.Checkpoint(epoch=bad.data.source.epoch,
                                          root=b"\x66" * 32)
    elif mode == "bad_target_epoch":
        bad.data.target = spec.Checkpoint(epoch=int(bad.data.target.epoch) + 3,
                                          root=bad.data.target.root)
    elif mode == "short_bits":
        bad.aggregation_bits = bad.aggregation_bits[:-1]
    return bad


@pytest.mark.parametrize("fork", ("phase0", "altair", "capella"))
@pytest.mark.parametrize("mode", ("bad_index", "bad_source",
                                  "bad_target_epoch", "short_bits"))
def test_invalid_attestation_rejection_parity(fork, mode):
    """An invalid attestation mid-batch must (a) raise in BOTH paths and
    (b) leave the SAME partial state behind — the oracle applies earlier
    valid attestations before raising, and so must the batch."""
    spec = build_spec(fork, "minimal")
    state, _ = _advanced_state(spec)
    rng = random.Random(42)
    atts = _random_batch(spec, state, rng, n=5)
    assert len(atts) >= 3
    atts[2] = _tampered(spec, atts[2], mode)

    def run(use_batch):
        st = state.copy()
        try:
            if use_batch:
                process_attestations_batch(spec, st, atts)
            else:
                for a in atts:
                    spec.process_attestation(st, a)
        except AssertionError:
            return "rejected", bytes(st.hash_tree_root())
        return "accepted", bytes(st.hash_tree_root())

    oracle = run(use_batch=False)
    batch = run(use_batch=True)
    assert oracle[0] == "rejected", f"tamper mode {mode} was not rejected"
    assert oracle == batch, f"{fork}/{mode}: rejection wreckage diverged"


@pytest.mark.parametrize("fork", FORKS)
def test_install_hook_routes_process_operations(fork):
    """use_batched_attestations(): the installed wrapper must make the
    FULL state_transition of a real attestation-carrying signed block
    bit-identical to the direct path, and uninstall must restore the
    spec function."""
    spec = build_spec(fork, "minimal")
    state = tf_context._prepare_state(
        tf_context.default_balances, tf_context.default_activation_threshold, spec)
    _, blocks, _ = next_slots_with_attestations(spec, state, 10, True, True)
    carrier = [b for b in blocks if len(b.message.body.attestations)]

    def replay():
        st = tf_context._prepare_state(
            tf_context.default_balances, tf_context.default_activation_threshold, spec)
        for b in blocks:
            spec.state_transition(st, b, True)
        return bytes(st.hash_tree_root())

    assert carrier
    direct = replay()
    engine.use_batched_attestations()
    try:
        assert engine.is_batched_attestations()
        assert getattr(spec.process_operations, "engine_batched_atts", False)
        batched = replay()
    finally:
        engine.use_direct_attestations()
    assert not getattr(spec.process_operations, "engine_batched_atts", False)
    assert direct == batched


def test_empty_batch_is_noop():
    spec = build_spec("altair", "minimal")
    state, _ = _advanced_state(spec, slots=4)
    before = bytes(state.hash_tree_root())
    process_attestations_batch(spec, state, [])
    assert bytes(state.hash_tree_root()) == before
