"""Backend quarantine-and-fallback: induced import/compile/dispatch
failures in the engine backend, the BLS facade, and the hashing backend
must (a) retry transients to success, (b) quarantine exactly once on a
deterministic fault, (c) hand every later call to the host path, and
(d) keep results bit-identical to the interpreted/reference oracle
throughout — degradation may never change an answer."""
from __future__ import annotations

import pytest

from consensus_specs_tpu import engine, resilience as r
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.engine import backend, crosscheck
from consensus_specs_tpu.specs import build_spec
from consensus_specs_tpu.ssz import hashing


@pytest.fixture(autouse=True)
def _clean_state():
    r.clear()
    r.events(clear=True)
    from consensus_specs_tpu.resilience import injection

    injection.disarm()
    yield
    r.clear()
    injection.disarm()
    engine.use_interpreted_epoch()
    engine.use_backend("numpy")
    bls.use_backend("reference")
    hashing.set_backend(None)


# ---------------------------------------------------------------------------
# engine backend
# ---------------------------------------------------------------------------

def _rewards_state(spec, seed=11):
    return crosscheck.random_epoch_state(spec, seed=seed, n_validators=64, epoch=3)


def test_engine_import_failure_degrades_to_numpy():
    with r.inject("engine.import", "environmental"):
        installed = engine.use_backend("jax")
    assert installed == "numpy"
    assert backend.active() == "numpy"
    assert r.is_quarantined("engine.jax")
    # results still correct: the numpy engine is the oracle-checked path
    spec = build_spec("altair", "minimal")
    same, *_ = crosscheck.crosscheck_stage(
        spec, "process_rewards_and_penalties", _rewards_state(spec))
    assert same


def test_engine_dispatch_deterministic_quarantines_once_numpy_takes_over():
    engine.use_backend("jax")
    saved = backend.DEVICE_MIN_ROWS
    backend.DEVICE_MIN_ROWS = 1  # force the dispatch path on a small registry
    try:
        spec = build_spec("altair", "minimal")
        with r.inject("engine.dispatch", "deterministic", count=-1):
            same, i_root, v_root = crosscheck.crosscheck_stage(
                spec, "process_rewards_and_penalties", _rewards_state(spec))
        # the injected kernel fault degraded to numpy mid-stage: still
        # bit-identical to the interpreted oracle
        assert same, f"fallback changed results: {i_root} != {v_root}"
        assert r.is_quarantined("engine.jax")
        quarantines = [e for e in r.events() if e["event"] == "quarantine"
                       and e["capability"] == "engine.jax"]
        assert len(quarantines) == 1
        # breaker open: the kernel is not offered anymore
        assert backend.delta_kernel() is None
        # and the stage keeps producing oracle-identical results
        same, *_ = crosscheck.crosscheck_stage(
            spec, "process_rewards_and_penalties", _rewards_state(spec, seed=12))
        assert same
    finally:
        backend.DEVICE_MIN_ROWS = saved


def test_engine_dispatch_transient_retried_to_success():
    engine.use_backend("jax")
    saved = backend.DEVICE_MIN_ROWS
    backend.DEVICE_MIN_ROWS = 1
    try:
        spec = build_spec("altair", "minimal")
        with r.inject("engine.dispatch", "transient", count=1):
            same, *_ = crosscheck.crosscheck_stage(
                spec, "process_rewards_and_penalties", _rewards_state(spec))
        assert same
        assert not r.is_quarantined("engine.jax")  # retry succeeded
        assert any(e["event"] == "retry" for e in r.events())
    finally:
        backend.DEVICE_MIN_ROWS = saved


# ---------------------------------------------------------------------------
# bls facade
# ---------------------------------------------------------------------------

_SK = 42
_MSG = b"\x5a" * 32


def _valid_check():
    from consensus_specs_tpu.crypto.bls import ciphersuite

    pk = ciphersuite.SkToPk(_SK)
    sig = ciphersuite.Sign(_SK, _MSG)
    return pk, _MSG, sig


class _StubDeviceBackend:
    """A 'device' backend the facade can quarantine without compiling
    anything: correct answers via the reference implementation."""

    def __init__(self):
        from consensus_specs_tpu.crypto.bls import ciphersuite

        self._ref = ciphersuite
        self.calls = 0

    def Verify(self, pk, msg, sig):
        self.calls += 1
        return self._ref.Verify(pk, msg, sig)

    def FastAggregateVerify(self, pks, msg, sig):
        self.calls += 1
        return self._ref.FastAggregateVerify(pks, msg, sig)

    def AggregateVerify(self, pks, msgs, sig):
        self.calls += 1
        return self._ref.AggregateVerify(pks, msgs, sig)


@pytest.fixture()
def stub_backend(monkeypatch):
    stub = _StubDeviceBackend()
    monkeypatch.setattr(bls, "_backend", stub)
    monkeypatch.setattr(bls, "_backend_name", "jax")
    return stub


def test_bls_import_failure_degrades_to_reference():
    with r.inject("bls.import", "environmental"):
        installed = bls.use_backend("jax")
    assert installed == "reference"
    assert bls.backend_name() == "reference"
    assert r.is_quarantined("bls.jax")
    pk, msg, sig = _valid_check()
    assert bls.Verify(pk, msg, sig) is True


def test_bls_dispatch_deterministic_quarantines_and_oracle_answers(stub_backend):
    from consensus_specs_tpu.crypto.bls import ciphersuite

    pk, msg, sig = _valid_check()
    with r.inject("bls.dispatch", "deterministic", count=-1):
        got = bls.Verify(pk, msg, sig)
    # the backend failed on a check the oracle ACCEPTS: defect -> quarantine
    assert got is ciphersuite.Verify(pk, msg, sig) is True
    assert r.is_quarantined("bls.jax")
    quarantines = [e for e in r.events() if e["event"] == "quarantine"
                   and e["capability"] == "bls.jax"]
    assert len(quarantines) == 1
    # breaker open: the stub is never called again, answers stay correct
    calls_before = stub_backend.calls
    assert bls.Verify(pk, msg, sig) is True
    assert bls.Verify(pk, msg, b"\x00" * 96) is False  # invalid sig, oracle says no
    assert stub_backend.calls == calls_before


def test_bls_dispatch_transient_retried_to_success(stub_backend):
    pk, msg, sig = _valid_check()
    with r.inject("bls.dispatch", "transient", count=1):
        assert bls.Verify(pk, msg, sig) is True
    assert not r.is_quarantined("bls.jax")
    assert stub_backend.calls == 1  # the retry reached the backend
    assert any(e["event"] == "retry" for e in r.events())


def test_bls_invalid_input_does_not_quarantine(stub_backend, monkeypatch):
    """A backend exception on an input the ORACLE also rejects is the
    spec's invalid-input surface, not a backend defect: answer False,
    keep the breaker closed."""
    def raising_verify(pk, msg, sig):
        raise ValueError("bad point encoding")

    monkeypatch.setattr(stub_backend, "Verify", raising_verify)
    pk, msg, _ = _valid_check()
    assert bls.Verify(pk, msg, b"\xff" * 96) is False
    assert not r.is_quarantined("bls.jax")


def test_bls_env_knob_drives_injection(stub_backend, monkeypatch):
    """The acceptance-criteria path: injection enabled via the env knob
    (not the fixture API) retries the transient to success."""
    monkeypatch.setenv(r.ENV_KNOB, "bls.dispatch=transient:1")
    r.refresh()
    try:
        pk, msg, sig = _valid_check()
        assert bls.Verify(pk, msg, sig) is True
        assert not r.is_quarantined("bls.jax")
    finally:
        monkeypatch.delenv(r.ENV_KNOB)
        r.refresh()


# ---------------------------------------------------------------------------
# hashing backend
# ---------------------------------------------------------------------------

def _install_stub_hasher(fail=False):
    calls = {"n": 0}

    def stub(data: bytes) -> bytes:
        calls["n"] += 1
        if fail:
            raise AssertionError("stub device hasher corrupted digest")
        return hashing._host_hash_many(data)

    hashing.set_backend(stub, "stub-device")
    return calls


def test_hash_dispatch_deterministic_quarantines_host_takes_over():
    data = b"\xab" * (64 * hashing.DEVICE_MIN_BLOCKS)
    want = hashing._host_hash_many(data)
    calls = _install_stub_hasher(fail=True)
    assert hashing.hash_many(data) == want  # fallback answered
    assert r.is_quarantined(hashing.HASH_CAPABILITY)
    n = calls["n"]
    assert hashing.hash_many(data) == want  # breaker open: host path
    assert calls["n"] == n


def test_hash_dispatch_transient_retried():
    data = b"\xcd" * (64 * hashing.DEVICE_MIN_BLOCKS)
    want = hashing._host_hash_many(data)
    _install_stub_hasher(fail=False)
    with r.inject("hash.dispatch", "transient", count=1):
        assert hashing.hash_many(data) == want
    assert not r.is_quarantined(hashing.HASH_CAPABILITY)


def test_hash_quarantine_keeps_tree_roots_identical():
    """End-to-end: a quarantined device hasher must not change a
    hash_tree_root (the host path is the same SHA-256)."""
    from consensus_specs_tpu.ssz import hash_tree_root
    from consensus_specs_tpu.ssz.types import List, uint64

    value = List[uint64, 1024](list(range(500)))
    want = bytes(hash_tree_root(value))
    _install_stub_hasher(fail=True)
    r.quarantine(hashing.HASH_CAPABILITY, "test-forced")
    assert bytes(hash_tree_root(value)) == want
