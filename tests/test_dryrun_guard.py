"""__graft_entry__.dryrun_multichip guard contract (ISSUE 4 satellite,
VERDICT r5 weak #1): the PARENT never initializes a jax backend (the
round-5 rc=124 was the parent blocking in jax.devices() under a wedged
tunnel, holding the GIL), and the INTERNAL deadline fires before any
external ``timeout -k`` — a hung child becomes a diagnosable
RuntimeError, not an opaque external kill."""
import os
import pathlib
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_default_internal_deadline_is_below_external_caps(monkeypatch):
    monkeypatch.delenv("GRAFT_DRYRUN_DEADLINE_S", raising=False)
    monkeypatch.delenv("GRAFT_EXTERNAL_TIMEOUT_S", raising=False)
    # the tier-1 harness's external cap is 870 s; the driver's dryrun cap
    # is at least that family — the internal default must sit below it
    assert __graft_entry__._internal_deadline() == 840.0
    assert __graft_entry__._internal_deadline() < 870.0


def test_deadline_clamped_under_advertised_external_timeout(monkeypatch):
    monkeypatch.setenv("GRAFT_EXTERNAL_TIMEOUT_S", "600")
    assert __graft_entry__._internal_deadline() == 570.0
    assert __graft_entry__._internal_deadline(500.0) == 500.0
    monkeypatch.setenv("GRAFT_EXTERNAL_TIMEOUT_S", "20")
    assert __graft_entry__._internal_deadline(840.0) == 1.0  # floor, never <= 0
    monkeypatch.setenv("GRAFT_EXTERNAL_TIMEOUT_S", "not-a-number")
    assert __graft_entry__._internal_deadline(123.0) == 123.0
    monkeypatch.delenv("GRAFT_EXTERNAL_TIMEOUT_S")
    monkeypatch.setenv("GRAFT_DRYRUN_DEADLINE_S", "77")
    assert __graft_entry__._internal_deadline() == 77.0


def _run_parent(code, env_extra, timeout):
    env = dict(os.environ)
    env.pop("CONSENSUS_SPECS_TPU_TRACE", None)
    env.update(env_extra)
    return subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                          env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_internal_deadline_fires_before_external_timeout():
    """A child wedged exactly like the dead tunnel (chaos 'hang' at the
    dryrun.child site) must be killed by the PARENT's internal deadline,
    well inside the external budget, with a diagnosable error."""
    code = (
        "import __graft_entry__, sys\n"
        "try:\n"
        "    __graft_entry__.dryrun_multichip(2, deadline_s=10)\n"
        "except RuntimeError as e:\n"
        "    assert 'deadline' in str(e), e\n"
        "    sys.exit(42)\n"
        "raise SystemExit('expected the internal deadline to fire')\n"
    )
    t0 = time.monotonic()
    proc = _run_parent(
        code, {"CONSENSUS_SPECS_TPU_CHAOS": "dryrun.child=hang"}, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 42, proc.stdout + proc.stderr
    # internal deadline (10 s) + child startup slack, far below the
    # 870 s-class external caps the driver uses
    assert elapsed < 100, f"deadline enforcement took {elapsed:.0f}s"


def test_parent_never_imports_jax():
    """The whole parent path — spawn, supervise, classify a child fault,
    raise — must complete without jax ever entering the parent process
    (the child imports it; the parent must not)."""
    code = (
        "import sys\n"
        "import __graft_entry__\n"
        "try:\n"
        "    __graft_entry__.dryrun_multichip(2, deadline_s=120)\n"
        "except RuntimeError as e:\n"
        "    assert 'deterministic' in str(e), e\n"
        "assert 'jax' not in sys.modules, 'parent imported jax'\n"
        "print('PARENT_PURE')\n"
    )
    proc = _run_parent(
        code, {"CONSENSUS_SPECS_TPU_CHAOS": "dryrun.child=deterministic"},
        timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PARENT_PURE" in proc.stdout
