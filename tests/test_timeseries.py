"""Tier-1 tests for the long-haul telemetry plane (ISSUE 13):
obs/timeseries.py journals + fork-reinit, obs/profile.py collapsed
stacks, the knob-unset zero-cost contract, the SIGKILL-mid-flush
crash drill, the mission report's byte stability, and the
events/histogram drop-count satellites."""
from __future__ import annotations

import importlib.util
import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time

import pytest

from consensus_specs_tpu import obs
from consensus_specs_tpu.obs import core as obs_core
from consensus_specs_tpu.obs import metrics as obs_metrics
from consensus_specs_tpu.obs import profile as obs_profile
from consensus_specs_tpu.obs import timeseries

REPO = pathlib.Path(__file__).resolve().parent.parent
MB = 1 << 20

_spec = importlib.util.spec_from_file_location(
    "mission_report", str(REPO / "tools" / "mission_report.py"))
assert _spec is not None and _spec.loader is not None
mission_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(mission_report)


@pytest.fixture()
def longhaul(tmp_path, monkeypatch):
    monkeypatch.setenv(timeseries.LONGHAUL_ENV, f"{tmp_path};0.02")
    yield tmp_path
    timeseries.stop()


def _series_files(d):
    return sorted(pathlib.Path(d).glob("series-*.jsonl"))


def _records(path):
    recs, _ = mission_report.parse_jsonl(str(path))
    return recs


# ---------------------------------------------------------------------------
# the knob-unset contract: zero cost, no threads, no allocation
# ---------------------------------------------------------------------------

def test_unarmed_is_free(monkeypatch):
    monkeypatch.delenv(timeseries.LONGHAUL_ENV, raising=False)
    monkeypatch.delenv(obs.TRACE_ENV, raising=False)
    before = threading.active_count()
    assert timeseries.ensure_started(role="nope") is False
    assert timeseries.active() is None
    assert obs_profile.active() is None
    assert threading.active_count() == before
    # the span fast path stays the shared no-op SINGLETON — zero
    # allocation, zero locks, whatever the long-haul plane does
    assert obs.span("x") is obs_core._NOOP
    timeseries.set_role("ignored")          # no-op, no crash
    timeseries.register_gauge("g", lambda: 1.0)
    timeseries.unregister_gauge("g")
    assert timeseries.stop() is None


# ---------------------------------------------------------------------------
# armed basics
# ---------------------------------------------------------------------------

def test_armed_journal_and_gauges(longhaul):
    assert timeseries.ensure_started(role="t.basic") is True
    obs_metrics.count("sim.blocks_proposed", 7)
    timeseries.register_gauge("t.depth", lambda: 42.0)
    fl = timeseries.active()
    assert fl is not None
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and fl.samples_written < 4:
        time.sleep(0.02)
    path = timeseries.stop()
    assert path is not None and os.path.exists(path)
    recs = _records(path)
    header = recs[0]
    assert header["type"] == "series_header"
    assert header["role"] == "t.basic"
    assert header["pid"] == os.getpid()
    samples = [r for r in recs if r["type"] == "sample"]
    assert len(samples) >= 4
    last = samples[-1]
    assert last["gauges"]["proc.rss_bytes"] > 0
    assert last["gauges"]["proc.cpu_s"] > 0
    assert last["gauges"]["proc.threads"] >= 1
    assert last["gauges"]["t.depth"] == 42.0
    assert last["counters"]["sim.blocks_proposed"] >= 7
    # timestamps are wall-anchored monotonic: strictly increasing
    ts = [s["ts"] for s in samples]
    assert ts == sorted(ts)
    timeseries.unregister_gauge("t.depth")


def test_ensure_started_idempotent_and_role_stickiness(longhaul):
    assert timeseries.ensure_started(role="first")
    fl = timeseries.active()
    assert timeseries.ensure_started(role="second")
    assert timeseries.active() is fl                   # same flusher
    assert fl.role == "first"                          # first explicit label sticks
    timeseries.set_role("relabelled")
    assert timeseries.ensure_started(role="generic")
    assert fl.role == "relabelled"


def test_knob_parsing(monkeypatch):
    monkeypatch.setenv(timeseries.LONGHAUL_ENV, "/tmp/x;0.5;43")
    assert timeseries.config_from_env() == ("/tmp/x", 0.5, 43.0)
    monkeypatch.setenv(timeseries.LONGHAUL_ENV, "/tmp/x;;0")
    assert timeseries.config_from_env() == ("/tmp/x", 1.0, 0.0)
    monkeypatch.setenv(timeseries.LONGHAUL_ENV, "/tmp/x")
    assert timeseries.config_from_env() == ("/tmp/x", 1.0, 19.0)
    monkeypatch.setenv(timeseries.LONGHAUL_ENV, "/tmp/x;bogus;bogus")
    assert timeseries.config_from_env() == ("/tmp/x", 1.0, 19.0)
    monkeypatch.delenv(timeseries.LONGHAUL_ENV)
    assert timeseries.config_from_env() is None


def test_postmortem_bundle(longhaul):
    assert timeseries.ensure_started(role="t.pm")
    fl = timeseries.active()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and fl.samples_written < 2:
        time.sleep(0.02)
    path = timeseries.postmortem_bundle("drill reason")
    assert path is not None
    with open(path) as f:
        pm = json.load(f)
    assert pm["reason"] == "drill reason"
    assert pm["role"] == "t.pm"
    assert len(pm["tail"]) >= 2
    assert "counters" in pm["snapshot"]


# ---------------------------------------------------------------------------
# profiler
# ---------------------------------------------------------------------------

def test_profiler_collapsed_stacks(tmp_path):
    def _busy_marker_fn():
        x = 0
        for i in range(120000):
            x += i * i
        return x

    assert obs_profile.arm(200, str(tmp_path)) is True
    assert obs_profile.armed()
    t_end = time.monotonic() + 0.4
    while time.monotonic() < t_end:
        _busy_marker_fn()
    out = obs_profile.disarm()
    assert out is not None and os.path.exists(out)
    content = open(out).read()
    assert "_busy_marker_fn" in content
    # collapsed format: "frame;frame;... <count>" per line
    for line in content.splitlines():
        stack, _, n = line.rpartition(" ")
        assert stack and int(n) >= 1
    assert obs_profile.disarm() is None   # idempotent
    assert not obs_profile.armed()


def test_longhaul_knob_arms_profiler(tmp_path, monkeypatch):
    monkeypatch.setenv(timeseries.LONGHAUL_ENV, f"{tmp_path};0.02;97")
    try:
        assert timeseries.ensure_started(role="t.prof")
        assert obs_profile.armed()
        t_end = time.monotonic() + 0.25
        while time.monotonic() < t_end:
            sum(i * i for i in range(10000))
    finally:
        timeseries.stop()
    assert not obs_profile.armed()
    profs = list(tmp_path.glob("profile-*.collapsed"))
    assert profs and profs[0].stat().st_size > 0


# ---------------------------------------------------------------------------
# SIGKILL mid-flush: the journal tail stays parseable, the merged
# report byte-stable (satellite drill)
# ---------------------------------------------------------------------------

def test_sigkill_mid_flush_tail_parseable(tmp_path):
    env = dict(os.environ)
    env[timeseries.LONGHAUL_ENV] = f"{tmp_path};0.01"
    code = (
        "import time\n"
        "from consensus_specs_tpu.obs import timeseries, metrics\n"
        "assert timeseries.ensure_started(role='kill.victim')\n"
        "print('armed', flush=True)\n"
        "while True:\n"
        "    metrics.count('work.items', 3)\n"
        "    time.sleep(0.004)\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", code], cwd=str(REPO),
                            env=env, stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout is not None
        assert proc.stdout.readline().strip() == "armed"
        deadline = time.monotonic() + 10
        # wait until the journal is visibly mid-stream, then SIGKILL
        while time.monotonic() < deadline:
            files = _series_files(tmp_path)
            if files and len(_records(files[0])) >= 6:
                break
            time.sleep(0.02)
        else:
            pytest.fail("victim never journaled 6 records")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(10)
    finally:
        if proc.poll() is None:
            proc.kill()
    (path,) = _series_files(tmp_path)
    recs, torn = mission_report.parse_jsonl(str(path))
    assert torn <= 1                       # at most the in-flight line
    assert recs[0]["type"] == "series_header"
    samples = [r for r in recs if r["type"] == "sample"]
    assert len(samples) >= 5
    assert samples[-1]["counters"]["work.items"] > 0
    # the merged report over the killed journal renders byte-stable
    html_a = mission_report.render_html(mission_report.load_run(str(tmp_path)))
    html_b = mission_report.render_html(mission_report.load_run(str(tmp_path)))
    assert html_a == html_b
    assert "kill.victim" in html_a


# ---------------------------------------------------------------------------
# fork_child_reinit: no inherited journals, no duplicate samplers
# (satellite drill — the fleet-replica / fuzz-rank / gen-shard path)
# ---------------------------------------------------------------------------

def test_fork_child_reinit_resets_flusher_and_profiler(tmp_path):
    env = dict(os.environ)
    env[timeseries.LONGHAUL_ENV] = f"{tmp_path};0.02;73"
    code = (
        "import json, os, sys, threading, time\n"
        "from consensus_specs_tpu import obs\n"
        "from consensus_specs_tpu.obs import metrics, profile, timeseries\n"
        "assert timeseries.ensure_started(role='fork.parent')\n"
        "metrics.count('parent.only', 11)\n"
        "parent_fl = timeseries.active()\n"
        "while parent_fl.samples_written < 2:\n"
        "    time.sleep(0.01)\n"
        "pid = os.fork()\n"
        "if pid == 0:\n"
        "    obs.fork_child_reinit(None)\n"
        "    timeseries.set_role('fork.child')\n"
        "    fl = timeseries.active()\n"
        "    assert fl is not None and fl is not parent_fl\n"
        "    assert fl.pid == os.getpid()\n"
        "    samplers = [t for t in threading.enumerate()\n"
        "                if t.name == 'obs-timeseries']\n"
        "    assert len(samplers) == 1, samplers\n"
        "    profs = [t for t in threading.enumerate()\n"
        "             if t.name == 'obs-profiler']\n"
        "    assert len(profs) == 1, profs\n"
        "    assert metrics.snapshot()['counters'].get('parent.only') is None\n"
        "    metrics.count('child.only', 5)\n"
        "    while fl.samples_written < 3:\n"
        "        time.sleep(0.01)\n"
        "    timeseries.stop()\n"
        "    os._exit(0)\n"
        "_, status = os.waitpid(pid, 0)\n"
        "assert status == 0, status\n"
        "timeseries.stop()\n"
        "print('forked ok', flush=True)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                          env=env, capture_output=True, text=True,
                          timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "forked ok" in proc.stdout
    files = _series_files(tmp_path)
    assert len(files) == 2, files            # one journal per process
    # roles resolve like the mission report does: the last sample's
    # role wins (a forked child's header lands before set_role runs)
    run = mission_report.load_run(str(tmp_path))
    by_role = {p["role"]: p for p in run["processes"]}
    assert set(by_role) == {"fork.parent", "fork.child"}
    assert by_role["fork.parent"]["pid"] != by_role["fork.child"]["pid"]
    # the child's aggregates started fresh: parent counters absent
    child_counters = by_role["fork.child"]["samples"][-1]["counters"]
    assert "parent.only" not in child_counters
    assert child_counters["child.only"] == 5


# ---------------------------------------------------------------------------
# satellites: event-buffer + histogram drop counting, gauges exposition
# ---------------------------------------------------------------------------

def test_events_dropped_counted(monkeypatch):
    monkeypatch.delenv(obs.TRACE_ENV, raising=False)
    obs.events(clear=True)
    base = obs.events_dropped()
    cap = obs_core._EVENTS.maxlen
    for i in range(cap + 25):
        obs.event("spam", i=i)
    assert obs.events_dropped() == base + 25
    assert len(obs.events()) == cap
    obs.events(clear=True)


def test_histogram_dropped_counted_and_exposed():
    obs_metrics.reset()
    try:
        for i in range(obs_metrics._HIST_CAP + 13):
            obs_metrics.observe("t_drop_ms", float(i % 7))
        snap = obs_metrics.snapshot()
        h = snap["histograms"]["t_drop_ms"]
        assert h["samples"] == obs_metrics._HIST_CAP
        assert h["dropped"] == 13
        assert h["count"] == obs_metrics._HIST_CAP + 13
        text = obs_metrics.prometheus_text(snap)
        assert "t_drop_ms_dropped 13" in text.splitlines()
        assert "# TYPE t_drop_ms_dropped counter" in text.splitlines()
    finally:
        obs_metrics.reset()


def test_gauges_in_snapshot_and_prometheus():
    obs_metrics.reset()
    try:
        obs_metrics.gauge("proc.rss_bytes", 12345.0)
        obs_metrics.gauge("proc.rss_bytes", 23456.0)   # last write wins
        snap = obs_metrics.snapshot()
        assert snap["gauges"] == {"proc.rss_bytes": 23456.0}
        text = obs_metrics.prometheus_text(snap)
        assert "# TYPE proc_rss_bytes gauge" in text.splitlines()
        assert "proc_rss_bytes 23456" in text.splitlines()
    finally:
        obs_metrics.reset()


def test_obs_overhead_polarity_and_unit():
    # the perfgate_obs_overhead_pct gate direction: lower is better,
    # unit is % (a rising overhead must be able to read as `regressed`)
    from consensus_specs_tpu.obs import ledger as ledger_mod
    from consensus_specs_tpu.obs import sentinel

    assert sentinel.polarity("perfgate_obs_overhead_pct") == -1
    assert ledger_mod.infer_unit("perfgate_obs_overhead_pct") == "%"
    # rates stay higher-is-better (the PR-12 regression pin)
    assert sentinel.polarity("fuzz_execs_per_s") == 1


# ---------------------------------------------------------------------------
# mission report over a synthetic multi-process run
# ---------------------------------------------------------------------------

def _write_series(d, name, role, pid, samples, findings=()):
    path = pathlib.Path(d) / name
    with open(path, "w") as f:
        f.write(json.dumps({"type": "series_header", "pid": pid,
                            "role": role, "interval_s": 1.0,
                            "ts": samples[0][0]}) + "\n")
        for ts, rss, n in samples:
            f.write(json.dumps({
                "type": "sample", "ts": ts, "role": role,
                "counters": {"work.items": n},
                "gauges": {"proc.rss_bytes": rss, "proc.cpu_s": ts / 1e6},
                "hists": {}}) + "\n")
        for rec in findings:
            f.write(json.dumps(rec) + "\n")
    return path


def test_mission_report_lanes_and_annotations(tmp_path):
    t0 = 1_700_000_000_000_000.0
    _write_series(tmp_path, "series-10-aaa.jsonl", "sim.driver", 10,
                  [(t0 + i * 1e6, (100 + i) * MB, 10 * i) for i in range(12)])
    _write_series(tmp_path, "series-20-bbb.jsonl", "fuzz.rank0", 20,
                  [(t0 + i * 1e6, (200 + 30 * i) * MB, 5 * i)
                   for i in range(12)],
                  findings=[{"type": "finding", "ts": t0 + 8e6,
                             "role": "fuzz.rank0", "pid": 20,
                             "kind": "rss_leak", "series": "proc.rss_bytes",
                             "detail": "rss slope 30.00 MB/s", "value": 30.0}])
    (tmp_path / "profile-10-aaa.collapsed").write_text(
        "main.py:main;sim.py:step 40\nmain.py:main;sim.py:attest 9\n")
    run = mission_report.load_run(str(tmp_path))
    summary = mission_report.summarize(run)
    assert summary["processes"] == 2
    assert summary["findings"] == 1
    assert summary["findings_by_kind"] == {"rss_leak": 1}
    assert summary["roles"] == ["fuzz.rank0", "sim.driver"]
    html_a = mission_report.render_html(run)
    html_b = mission_report.render_html(mission_report.load_run(str(tmp_path)))
    assert html_a == html_b                      # byte-stable
    assert "sim.driver" in html_a and "fuzz.rank0" in html_a
    assert "rss_leak" in html_a                  # anomaly annotation
    assert "sim.py:step" in html_a               # profile table
    assert html_a.count("<svg") >= 3             # sparkline lanes


def test_mission_report_bundle(tmp_path):
    t0 = 1_700_000_000_000_000.0
    _write_series(tmp_path, "series-10-aaa.jsonl", "r", 10,
                  [(t0 + i * 1e6, 100 * MB, i) for i in range(50)])
    out = tmp_path / "bundle"
    manifest = mission_report.collect_bundle(str(tmp_path), str(out), tail=10)
    assert (out / "MANIFEST.json").exists()
    kept = (out / "series-10-aaa.jsonl").read_text().splitlines()
    assert len(kept) == 10                       # the tail only
    assert json.loads(kept[-1])["counters"]["work.items"] == 49
    assert manifest["files"][0]["lines_total"] == 51
